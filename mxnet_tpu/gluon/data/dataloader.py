"""Gluon DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes and ships NDArrays through POSIX shared
memory (cpu_shared context, dataloader.py:26-110).  Two worker modes here:

- ``thread_workers=True`` (or ``num_workers>0`` with small pipelines):
  a thread pool — batch assembly is numpy (releases the GIL in practice)
  and device transfer is XLA-async.
- ``num_workers>0`` (default mode): true **worker processes** with batches
  returned through POSIX shared memory (`multiprocessing.shared_memory`),
  the TPU-era equivalent of the reference's cpu_shared NDArray IPC — heavy
  Python-side augmentation scales past the GIL.  Workers are *spawned*
  (never forked) and pin ``JAX_PLATFORMS=cpu`` before any jax import so
  they can never grab the TPU from the training process.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

log = logging.getLogger(__name__)

from ... import ndarray as nd
from ... import sanitizer as _san
from ...observability import metrics as _obs_metrics

# module-level ref — sampled once per consumed batch
_INFLIGHT_BATCHES = _obs_metrics.gauge(
    "dataloader_inflight_batches",
    "batches issued to DataLoader workers but not yet consumed")
from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


# ---------------------------------------------------------------------------
# Multiprocess worker machinery (reference: dataloader.py:26-110 —
# worker_loop + rebuild_ndarray via cpu_shared storage).
# ---------------------------------------------------------------------------

def _np_batchify(data):
    """Worker-side batchify: like default_batchify_fn but with numpy
    leaves (workers never build device arrays)."""
    first = data[0]
    if isinstance(first, NDArray):
        return _np.stack([d.asnumpy() for d in data])
    if isinstance(first, tuple):
        return tuple(_np_batchify(list(i)) for i in zip(*data))
    if isinstance(first, list):
        return [_np_batchify(list(i)) for i in zip(*data)]
    a = _np.asarray(data)
    return a.astype(_np.float32) if a.dtype == _np.float64 else a


def _tree_to_shm(obj):
    """numpy leaves -> ('shm', name, shape, dtype) descriptors; the parent
    owns the segment lifecycle (workers unregister from their tracker)."""
    from multiprocessing import shared_memory, resource_tracker
    if isinstance(obj, _np.ndarray):
        if obj.nbytes == 0:
            return ("raw", obj)
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        view = _np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        view[...] = obj
        name = shm.name
        # parent unlinks; drop this process's tracker registration so the
        # worker's exit doesn't double-unlink
        try:
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception as exc:
            # tracker internals vary across Pythons; a failed
            # unregister only risks a spurious tracker warning at
            # worker exit — keep it diagnosable, not fatal
            log.debug("shm tracker unregister failed for %s: %s",
                      shm._name, exc)
        shm.close()
        return ("shm", name, obj.shape, str(obj.dtype))
    if isinstance(obj, tuple):
        return ("tuple", [_tree_to_shm(o) for o in obj])
    if isinstance(obj, list):
        return ("list", [_tree_to_shm(o) for o in obj])
    return ("raw", obj)


def _tree_from_shm(desc):
    """Rebuild NDArray leaves from shared-memory descriptors (parent)."""
    from multiprocessing import shared_memory
    tag = desc[0]
    if tag == "shm":
        _, name, shape, dtype = desc
        shm = shared_memory.SharedMemory(name=name)
        try:
            view = _np.ndarray(shape, dtype, buffer=shm.buf)
            # explicit host copy: jax's CPU backend may alias numpy
            # buffers zero-copy, and the segment is about to be unmapped
            arr = nd.array(_np.array(view), dtype=dtype)
        finally:
            shm.close()
            shm.unlink()
        return arr
    if tag == "tuple":
        return tuple(_tree_from_shm(d) for d in desc[1])
    if tag == "list":
        return [_tree_from_shm(d) for d in desc[1]]
    val = desc[1]
    return nd.array(val) if isinstance(val, _np.ndarray) else val


def _worker_loop(dataset, batchify_fn, work_q, res_q):
    """Long-lived worker: pull (seq, indices), push (seq, shm_tree, err)."""
    while True:
        job = work_q.get()
        if job is None:
            break
        seq, indices = job
        try:
            batch = batchify_fn([dataset[i] for i in indices])
            res_q.put((seq, _tree_to_shm(batch), None))
        except Exception:
            # the traceback travels to the consumer and is raised there;
            # log here too so a worker whose result is never consumed
            # (shutdown race) still leaves a trace
            log.debug("dataloader worker failed on batch %d:\n%s", seq,
                      traceback.format_exc())
            res_q.put((seq, None, traceback.format_exc()))


class _MultiWorkerIter:
    """Ordered iterator over worker-process results (reference:
    dataloader.py _MultiWorkerIter with rcvd_idx ordering).

    Each worker owns a PRIVATE index queue (jobs are round-robined):
    a worker killed while blocked in ``Queue.get`` dies holding that
    queue's reader semaphore, and with a shared queue that one death
    would wedge every other reader forever.  Private queues make a
    crashed worker fully disposable — its queue is dropped, a
    replacement is spawned (with retry/backoff) onto a fresh queue,
    and exactly the batches assigned to the dead worker are
    resubmitted."""

    def __init__(self, dataset, batchify_fn, batch_sampler, num_workers,
                 prefetch, max_respawns=None):
        import multiprocessing as mp
        # spawn, never fork: the parent holds live XLA/TPU state that must
        # not leak into children; spawned children re-import under
        # JAX_PLATFORMS=cpu (set in the env below, inherited at exec)
        self._ctx = mp.get_context("spawn")
        self._dataset = dataset
        self._batchify_fn = batchify_fn
        self._res_q = self._ctx.Queue()
        if max_respawns is None:
            from ...config import get_env
            max_respawns = get_env("MXNET_DATALOADER_RESPAWNS")
        self._max_respawns = max(0, max_respawns)
        self._respawns = 0
        self._work_qs = [self._ctx.Queue() for _ in range(num_workers)]
        self._workers = [self._spawn_worker(q) for q in self._work_qs]
        self._batches = iter(batch_sampler)
        self._sent = 0
        self._rcvd = 0
        self._buffer = {}
        self._inflight = {}     # seq -> (worker slot, indices)
        self._exhausted = False
        for _ in range(prefetch):
            self._push_next()

    #: two loaders (or a loader and a respawn) starting workers
    #: concurrently would interleave their os.environ mutation and
    #: could leak JAX_PLATFORMS=cpu into the parent permanently —
    #: serialize the mutate-start-restore window
    _spawn_env_lock = _san.lock(label="dataloader._spawn_env_lock")

    def _spawn_worker(self, work_q):
        worker = self._ctx.Process(
            target=_worker_loop,
            args=(self._dataset, self._batchify_fn, work_q,
                  self._res_q),
            daemon=True)
        # children inherit the env at start(): pin cpu for them only
        with self._spawn_env_lock:
            prev = os.environ.get("JAX_PLATFORMS")
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                worker.start()
            finally:
                if prev is None:
                    del os.environ["JAX_PLATFORMS"]
                else:
                    os.environ["JAX_PLATFORMS"] = prev
        return worker

    def _push_next(self):
        try:
            indices = next(self._batches)
        except StopIteration:
            self._exhausted = True
            return
        slot = self._sent % len(self._workers)
        self._inflight[self._sent] = (slot, indices)
        self._work_qs[slot].put((self._sent, indices))
        self._sent += 1

    def _revive_dead_workers(self):
        """Respawn crashed workers (retry/backoff on the spawn itself)
        onto fresh queues and resubmit exactly the batches the dead
        workers owned.  False when the respawn budget is exhausted."""
        dead = [i for i, w in enumerate(self._workers)
                if not w.is_alive()]
        if not dead:
            return True
        if self._respawns + len(dead) > self._max_respawns:
            return False
        from ...resilience.retry import retry_call
        from ...observability import events as _obs_events
        from ...observability import metrics as _metrics
        for i in dead:
            w = self._workers[i]
            log.warning("DataLoader worker pid=%s died (exitcode=%s); "
                        "respawning (%d/%d respawns used)", w.pid,
                        w.exitcode, self._respawns + 1,
                        self._max_respawns)
            self._respawns += 1
            _metrics.counter("dataloader_worker_respawns_total",
                             "dead DataLoader workers respawned").inc()
            _obs_events.emit("respawn", what="dataloader_worker",
                             slot=i, pid=w.pid, exitcode=w.exitcode,
                             used=self._respawns,
                             budget=self._max_respawns)
            # the dead worker's queue may be semaphore-poisoned (killed
            # mid-get) — discard it wholesale
            self._work_qs[i] = self._ctx.Queue()
            self._workers[i] = retry_call(
                self._spawn_worker, (self._work_qs[i],), attempts=3,
                base_delay=0.05, max_delay=0.5,
                retry_on=(OSError, RuntimeError))
            for seq in range(self._rcvd, self._sent):
                if seq in self._buffer or seq not in self._inflight:
                    continue
                slot, indices = self._inflight[seq]
                if slot == i:
                    self._work_qs[i].put((seq, indices))
        return True

    def __iter__(self):
        return self

    #: consecutive result-less seconds with live workers before the
    #: loader concludes the SHARED result queue is wedged (a worker
    #: killed mid-put can die holding its write lock — the one shared
    #: resource respawning cannot replace) and fails loudly
    _STALL_LIMIT_S = 60

    def __next__(self):
        # queue depth = batches issued to workers but not yet consumed
        # (sampled per batch: a scraper watching this gauge fall to 0
        # has found an input-bound training loop)
        _INFLIGHT_BATCHES.set(self._sent - self._rcvd)
        if self._rcvd == self._sent:
            self.shutdown()
            raise StopIteration
        stalled = 0
        while self._rcvd not in self._buffer:
            if stalled >= self._STALL_LIMIT_S:
                self.shutdown()
                raise RuntimeError(
                    "DataLoader produced no batch for %ds despite live "
                    "workers — the shared result queue is likely "
                    "poisoned (a worker was killed while holding its "
                    "write lock). Restart the loader; lower batch "
                    "sizes/augmentation cost if workers are being "
                    "OOM-killed." % self._STALL_LIMIT_S)
            try:
                seq, payload, err = self._res_q.get(timeout=1.0)
            except queue.Empty:
                stalled += 1
                # liveness check: a crashed worker (OOM-kill, segfault,
                # failed spawn import) would otherwise hang this get
                # forever — workers only exit after the shutdown sentinel
                if any(not w.is_alive() for w in self._workers) and \
                        not self._revive_dead_workers():
                    self.shutdown()
                    raise RuntimeError(
                        "DataLoader worker died unexpectedly (killed or "
                        "crashed before producing its batch; %d "
                        "respawn(s) already attempted). If this "
                        "happened at startup, the training script likely "
                        "lacks an `if __name__ == \"__main__\":` guard — "
                        "workers are spawned (never forked: the parent "
                        "holds live XLA/TPU state), so the main module "
                        "must be importable; alternatively pass "
                        "thread_workers=True." % self._respawns)
                continue
            if seq < self._rcvd or seq in self._buffer:
                # duplicate delivery after a respawn resubmission: the
                # original worker produced it after all — drop it and
                # unlink its shm segments
                if payload is not None:
                    self._unlink_tree(payload)
                continue
            stalled = 0
            self._buffer[seq] = (payload, err)
        payload, err = self._buffer.pop(self._rcvd)
        self._inflight.pop(self._rcvd, None)
        self._rcvd += 1
        self._push_next()
        if err is not None:
            self.shutdown()
            raise RuntimeError("DataLoader worker failed:\n%s" % err)
        return _tree_from_shm(payload)

    @staticmethod
    def _unlink_tree(desc):
        """Release shm segments of an unconsumed result (workers
        unregistered them from their tracker; the parent owns cleanup)."""
        from multiprocessing import shared_memory
        tag = desc[0]
        if tag == "shm":
            try:
                shm = shared_memory.SharedMemory(name=desc[1])
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass
        elif tag in ("tuple", "list"):
            for d in desc[1]:
                _MultiWorkerIter._unlink_tree(d)

    def shutdown(self):
        for q in self._work_qs:
            try:
                q.put(None)
            except (OSError, ValueError) as exc:
                # queue already closed/broken mid-teardown: the join
                # below falls back to terminate(), but say what happened
                log.debug("work queue rejected shutdown sentinel: %s",
                          exc)
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        self._workers = []
        self._work_qs = []
        # drain prefetched-but-unconsumed results: their shm segments
        # survive process exit unless unlinked here (early `break` from a
        # training loop would otherwise leak /dev/shm permanently)
        while True:
            try:
                seq, payload, err = self._res_q.get_nowait()
            except (queue.Empty, OSError, ValueError):
                break
            self._buffer[seq] = (payload, err)
        for payload, _err in self._buffer.values():
            if payload is not None:
                self._unlink_tree(payload)
        self._buffer.clear()

    def __del__(self):
        if getattr(self, "_workers", None):
            self.shutdown()


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=str(data.dtype)
                    if data.dtype != _np.float64 else "float32")


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None, thread_workers=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    # a private, captured seed (drawn once from the
                    # global stream, so np.random.seed reproducibility
                    # is preserved) makes the shuffle order resumable
                    # through state_dict() — see docs/resilience.md
                    sampler = RandomSampler(
                        len(dataset),
                        seed=int(_np.random.randint(0, 2 ** 31 - 1)))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._thread_workers = thread_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._cursor = 0        # batches delivered this epoch
        self._resume_skip = 0   # pending load_state fast-forward
        self._worker_iter = None  # live _MultiWorkerIter, if any
        self._mp_ok = None
        if self._num_workers > 0 and not thread_workers:
            # probe once (not per epoch): spawn needs picklable
            # dataset/batchify — the reference's Windows-path constraint
            batchify = (self._batchify_fn
                        if self._batchify_fn is not default_batchify_fn
                        else _np_batchify)
            try:
                import pickle

                # stream to a discarding sink: pickle.dumps would
                # materialize a full serialized copy of the dataset
                # (momentarily doubling memory for big in-memory sets)
                # just to learn whether pickling WORKS
                class _Null:
                    def write(self, b):
                        return len(b)
                pickle.Pickler(_Null()).dump(self._dataset)
                pickle.Pickler(_Null()).dump(batchify)
                self._mp_ok = True
            except Exception as exc:
                import warnings
                warnings.warn(
                    "DataLoader: dataset/batchify_fn not picklable "
                    "(%s: %s); using thread workers instead of "
                    "processes" % (type(exc).__name__, exc))
                self._mp_ok = False

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    # -- resumable position (resilience subsystem) -------------------------
    def state_dict(self):
        """Mid-epoch resume position: the batch cursor (batches
        DELIVERED to the consumer this epoch — the worker-respawn
        machinery below this level resubmits crashed workers' batches,
        so issued-but-unconsumed work is deliberately not counted)
        plus the sampler's shuffle-order state."""
        st = {"type": "DataLoader", "cursor": int(self._cursor)}
        sd = getattr(self._batch_sampler, "state_dict", None)
        if sd is not None:
            st["batch_sampler"] = sd()
        return st

    def load_state(self, state):
        """Restore a :meth:`state_dict` position: the next ``iter()``
        regenerates the in-progress epoch (the sampler rewinds and
        re-draws its exact permutation, rollover leftovers included)
        and skips the already-consumed batches — index skipping only,
        no decode work is replayed."""
        if state.get("type") not in (None, "DataLoader"):
            raise ValueError("not a DataLoader state: %r"
                             % (state.get("type"),))
        bs = state.get("batch_sampler")
        cursor = int(state["cursor"])
        if bs is not None and \
                getattr(self._batch_sampler, "load_state", None):
            self._batch_sampler.load_state(bs, in_progress=cursor > 0)
            if getattr(self._batch_sampler, "exact_resume", False):
                # the sampler resumes at its own exact (global) cursor
                # — e.g. ElasticBatchSampler, whose batch->sample
                # mapping changes across resizes, so fast-forwarding
                # by delivered-batch count would skip the wrong work
                self._resume_skip = 0
                return
        self._resume_skip = cursor

    def repartition(self, part_index, num_parts):
        """Elastic re-shard (docs/resilience.md "Elastic training"):
        delegate to the batch sampler — with an
        :class:`~mxnet_tpu.gluon.data.ElasticBatchSampler` the change
        takes effect at the next yielded batch, mid-epoch included.

        Mid-epoch re-sharding requires the synchronous
        ``num_workers=0`` path: a worker-prefetched loader has already
        issued indices prefetch-depth batches past the consumer, and
        that skew differs per rank — the fleet would switch layouts at
        different global rounds, consuming some samples twice and
        others never.  A live multi-process iteration therefore
        refuses; repartition between epochs (no live iterator) is fine
        in any mode."""
        rp = getattr(self._batch_sampler, "repartition", None)
        if rp is None:
            raise AttributeError(
                "DataLoader.repartition needs a batch sampler with "
                "repartition() (e.g. ElasticBatchSampler); got %s"
                % type(self._batch_sampler).__name__)
        if self._worker_iter is not None:
            raise RuntimeError(
                "DataLoader.repartition mid-epoch over process workers "
                "would re-shard prefetch-depth batches late (and by a "
                "per-rank amount — exactly-once coverage breaks): use "
                "num_workers=0 for elastic training, or repartition "
                "between epochs")
        rp(part_index, num_parts)

    def __iter__(self):
        skip = self._resume_skip
        self._resume_skip = 0
        self._cursor = skip
        for batch in self._iter_batches(skip):
            self._cursor += 1
            yield batch

    def _skip_batches(self, skip):
        """Iterator over the epoch's index lists minus the first
        *skip* (cheap: indices only, nothing is decoded)."""
        it = iter(self._batch_sampler)
        for _ in range(skip):
            try:
                next(it)
            except StopIteration:
                return iter(())
        return it

    def _iter_batches(self, skip):
        batches_src = self._skip_batches(skip) if skip else \
            iter(self._batch_sampler)
        if self._num_workers == 0:
            for indices in batches_src:
                yield self._make_batch(indices)
            return
        if not self._thread_workers and self._mp_ok:
            # process workers + shared-memory transport
            batchify = (self._batchify_fn
                        if self._batchify_fn is not default_batchify_fn
                        else _np_batchify)
            it = _MultiWorkerIter(
                self._dataset, batchify, batches_src,
                self._num_workers,
                prefetch=max(self._prefetch, self._num_workers))
            # exposed for respawn-bookkeeping introspection (tests,
            # job-state capture coordination)
            self._worker_iter = it
            try:
                yield from it
            finally:
                # early break from the consuming loop must still reap
                # workers and unlink prefetched shm segments
                it.shutdown()
                self._worker_iter = None
            return
        # threaded prefetch: submit up to `prefetch` batch jobs ahead
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            batches = batches_src
            futures = []
            try:
                for _ in range(self._prefetch or self._num_workers * 2):
                    futures.append(pool.submit(self._make_batch,
                                               next(batches)))
            except StopIteration:
                pass
            while futures:
                fut = futures.pop(0)
                try:
                    futures.append(pool.submit(self._make_batch,
                                               next(batches)))
                except StopIteration:
                    pass
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
