"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""

from __future__ import annotations

import numpy as _np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "ElasticBatchSampler"]


class Sampler:
    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Uniform shuffle.  With *seed*, each epoch's permutation is
    drawn from a PRIVATE ``RandomState([seed, epoch])`` stream, which
    makes the shuffle order resumable: ``state_dict()`` records
    ``(seed, epochs drawn)`` and a restored sampler re-draws the
    in-progress epoch's exact permutation.  Without a seed the legacy
    global-``np.random`` behavior is kept (order not capturable)."""

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._drawn = 0         # permutations handed out so far

    def __iter__(self):
        if self._seed is None:
            indices = _np.random.permutation(self._length)
        else:
            rs = _np.random.RandomState([self._seed, self._drawn])
            indices = rs.permutation(self._length)
        self._drawn += 1
        return iter(indices.tolist())

    def __len__(self):
        return self._length

    def state_dict(self):
        return {"seed": self._seed, "drawn": self._drawn}

    def load_state(self, state, in_progress=False):
        """Restore the stream position.  *in_progress* = the captured
        state was taken mid-epoch: rewind one draw so the next
        ``iter()`` regenerates that epoch's exact permutation."""
        self._seed = state["seed"]
        self._drawn = int(state["drawn"])
        if in_progress and self._drawn > 0:
            self._drawn -= 1


class ElasticBatchSampler(Sampler):
    """Worker-sharded batches over a SHARED deterministic global order
    — the gluon-side elastic partition (docs/resilience.md "Elastic
    training").

    Every worker constructs it with the same ``(length, batch_size,
    seed)``; epoch *e*'s global order is drawn from
    ``RandomState([seed, e])`` (or ``arange`` when ``shuffle=False``),
    walked in GLOBAL rounds of ``batch_size * num_parts`` samples, and
    each worker yields only its ``part_index``-th slice of each round
    — so the union of all parts covers each epoch index exactly once.

    ``repartition()`` re-shards at the next batch boundary: the
    generator reads the partition and the global cursor live, so a
    mid-epoch shrink/grow keeps exactly-once coverage.  A mid-epoch
    joiner restores a survivor's ``state_dict()`` (``load_state(...,
    in_progress=True)`` resumes at the exact global cursor — the
    sampler sets ``exact_resume`` so DataLoader does no extra batch
    skipping) and repartitions to its own slot; the post-resize stream
    is bit-reproducible from that state alone.

    ``last_batch``: ``'discard'`` drops a final partial global round;
    ``'keep'`` splits its tail contiguously by position (ragged or
    empty per-worker batches — exactly-once, no padding)."""

    #: DataLoader.load_state: this sampler resumes at its own exact
    #: global cursor; do NOT fast-forward by delivered-batch count
    #: (batch->sample mapping changes across resizes).
    exact_resume = True

    def __init__(self, length, batch_size, part_index=0, num_parts=1,
                 shuffle=True, seed=0, last_batch="discard"):
        if last_batch not in ("discard", "keep"):
            raise ValueError("last_batch must be 'discard' or 'keep', "
                             "got %r" % (last_batch,))
        self._length = int(length)
        self._batch_size = int(batch_size)
        self._shuffle = bool(shuffle)
        self._seed = int(seed)
        self._last_batch = last_batch
        self._part = 0
        self._parts = 1
        self.repartition(part_index, num_parts)
        self._drawn = 0      # epochs begun
        self._epoch = -1     # epoch currently iterating
        self._cursor = 0     # global samples consumed this epoch
        self._pending = None  # (epoch, cursor) resume position

    def repartition(self, part_index, num_parts):
        """Become slice *part_index* of *num_parts* starting at the
        NEXT batch boundary (the live generator reads these fields per
        round; the global cursor is untouched)."""
        part_index, num_parts = int(part_index), int(num_parts)
        if not 0 <= part_index < num_parts:
            raise ValueError("part_index %d not in [0, %d)"
                             % (part_index, num_parts))
        if self._length < self._batch_size * num_parts:
            raise ValueError(
                "global batch (batch_size %d * num_parts %d) must not "
                "exceed the dataset length %d"
                % (self._batch_size, num_parts, self._length))
        self._part, self._parts = part_index, num_parts

    def _order(self, epoch):
        if not self._shuffle:
            return _np.arange(self._length)
        return _np.random.RandomState(
            [self._seed, epoch]).permutation(self._length)

    def __iter__(self):
        if self._pending is not None:
            epoch, cursor = self._pending
            self._pending = None
        else:
            epoch, cursor = self._drawn, 0
        self._epoch = epoch
        self._drawn = epoch + 1
        self._cursor = cursor
        order = self._order(epoch)
        n = self._length
        while True:
            b = self._batch_size
            round_ = b * self._parts
            start = self._cursor
            if start >= n:
                return
            if start + round_ > n:
                if self._last_batch == "discard":
                    self._cursor = n
                    return
                # 'keep': the tail splits contiguously by position
                tail = order[start:]
                lo = min(self._part * b, len(tail))
                hi = min(lo + b, len(tail))
                self._cursor = n
                if hi > lo:
                    yield [int(i) for i in tail[lo:hi]]
                return
            sel = order[start + self._part * b:
                        start + (self._part + 1) * b]
            self._cursor = start + round_
            yield [int(i) for i in sel]

    def __len__(self):
        round_ = self._batch_size * self._parts
        full = self._length // round_
        if self._last_batch == "discard":
            return full
        # 'keep': the tail splits contiguously by position — THIS
        # part yields a final (ragged) batch only if the tail reaches
        # its slice
        tail = self._length - full * round_
        return full + (1 if tail > self._part * self._batch_size
                       else 0)

    def state_dict(self):
        return {"type": type(self).__name__,
                "seed": self._seed, "shuffle": self._shuffle,
                "epoch": self._epoch, "drawn": self._drawn,
                "cursor": int(self._cursor),
                "part_index": self._part, "num_parts": self._parts}

    def load_state(self, state, in_progress=False):
        """Restore; *in_progress* resumes the captured epoch at its
        exact global cursor (a joiner then ``repartition()``s to its
        own slot), otherwise the next ``iter()`` starts the next
        epoch in lockstep with the captured stream."""
        self._seed = int(state["seed"])
        self._shuffle = bool(state.get("shuffle", True))
        self._drawn = int(state["drawn"])
        self.repartition(int(state.get("part_index", 0)),
                         int(state.get("num_parts", 1)))
        if in_progress:
            self._pending = (int(state["epoch"]),
                             int(state["cursor"]))
        else:
            self._pending = None


class BatchSampler(Sampler):
    """Wrap a sampler into batches; last_batch in {keep, discard,
    rollover}."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        self._epoch_prev = []   # leftovers the CURRENT epoch started with

    def __iter__(self):
        batch, self._prev = self._prev, []
        # remember what this epoch consumed from the previous one: a
        # mid-epoch resume must regenerate the SAME epoch stream,
        # leftovers included (rollover semantics)
        self._epoch_prev = list(batch)
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of 'keep', 'discard', or "
                    "'rollover', but got %s" % self._last_batch)

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // \
                self._batch_size
        raise ValueError("last_batch must be one of 'keep', 'discard', or "
                         "'rollover', but got %s" % self._last_batch)

    def state_dict(self):
        st = {"prev": list(self._prev),
              "epoch_prev": list(self._epoch_prev)}
        sd = getattr(self._sampler, "state_dict", None)
        if sd is not None:
            st["sampler"] = sd()
        return st

    def load_state(self, state, in_progress=False):
        """Restore; *in_progress* = the state was captured mid-epoch,
        so the next ``iter()`` must REGENERATE that epoch — it starts
        from the leftovers that epoch consumed, and the inner sampler
        rewinds to re-draw its permutation."""
        if in_progress:
            self._prev = list(state.get("epoch_prev") or [])
        else:
            self._prev = list(state.get("prev") or [])
        inner = state.get("sampler")
        if inner is not None:
            try:
                self._sampler.load_state(inner, in_progress=in_progress)
            except TypeError:
                # custom sampler without the flag: positional restore
                self._sampler.load_state(inner)
