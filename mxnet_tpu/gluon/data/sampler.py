"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""

from __future__ import annotations

import numpy as _np

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler"]


class Sampler:
    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Uniform shuffle.  With *seed*, each epoch's permutation is
    drawn from a PRIVATE ``RandomState([seed, epoch])`` stream, which
    makes the shuffle order resumable: ``state_dict()`` records
    ``(seed, epochs drawn)`` and a restored sampler re-draws the
    in-progress epoch's exact permutation.  Without a seed the legacy
    global-``np.random`` behavior is kept (order not capturable)."""

    def __init__(self, length, seed=None):
        self._length = length
        self._seed = seed
        self._drawn = 0         # permutations handed out so far

    def __iter__(self):
        if self._seed is None:
            indices = _np.random.permutation(self._length)
        else:
            rs = _np.random.RandomState([self._seed, self._drawn])
            indices = rs.permutation(self._length)
        self._drawn += 1
        return iter(indices.tolist())

    def __len__(self):
        return self._length

    def state_dict(self):
        return {"seed": self._seed, "drawn": self._drawn}

    def load_state(self, state, in_progress=False):
        """Restore the stream position.  *in_progress* = the captured
        state was taken mid-epoch: rewind one draw so the next
        ``iter()`` regenerates that epoch's exact permutation."""
        self._seed = state["seed"]
        self._drawn = int(state["drawn"])
        if in_progress and self._drawn > 0:
            self._drawn -= 1


class BatchSampler(Sampler):
    """Wrap a sampler into batches; last_batch in {keep, discard,
    rollover}."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []
        self._epoch_prev = []   # leftovers the CURRENT epoch started with

    def __iter__(self):
        batch, self._prev = self._prev, []
        # remember what this epoch consumed from the previous one: a
        # mid-epoch resume must regenerate the SAME epoch stream,
        # leftovers included (rollover semantics)
        self._epoch_prev = list(batch)
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of 'keep', 'discard', or "
                    "'rollover', but got %s" % self._last_batch)

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // \
                self._batch_size
        raise ValueError("last_batch must be one of 'keep', 'discard', or "
                         "'rollover', but got %s" % self._last_batch)

    def state_dict(self):
        st = {"prev": list(self._prev),
              "epoch_prev": list(self._epoch_prev)}
        sd = getattr(self._sampler, "state_dict", None)
        if sd is not None:
            st["sampler"] = sd()
        return st

    def load_state(self, state, in_progress=False):
        """Restore; *in_progress* = the state was captured mid-epoch,
        so the next ``iter()`` must REGENERATE that epoch — it starts
        from the leftovers that epoch consumed, and the inner sampler
        rewinds to re-draw its permutation."""
        if in_progress:
            self._prev = list(state.get("epoch_prev") or [])
        else:
            self._prev = list(state.get("prev") or [])
        inner = state.get("sampler")
        if inner is not None:
            try:
                self._sampler.load_state(inner, in_progress=in_progress)
            except TypeError:
                # custom sampler without the flag: positional restore
                self._sampler.load_state(inner)
