"""Contrib layers (reference: gluon/contrib/nn/basic_layers.py)."""

from .basic_layers import (Concurrent, HybridConcurrent, Identity,  # noqa
                           MoEFFN, MultiHeadAttention, SparseEmbedding,
                           SyncBatchNorm)
