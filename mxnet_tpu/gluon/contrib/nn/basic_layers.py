"""Contrib layers (reference: gluon/contrib/nn/basic_layers.py:29-208 —
Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm)."""

from __future__ import annotations

from ... import nn
from ...block import Block, HybridBlock
from .... import ndarray as nd


class Concurrent(nn.Sequential):
    """Run children on the same input, concat outputs along *axis*."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [blk(x) for blk in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable :class:`Concurrent`."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [blk(x) for blk in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (useful as a Concurrent branch)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose gradient is row_sparse (reference:
    basic_layers.py:116 — sparse_grad Embedding for kvstore
    row_sparse_pull training).  Forward is a row gather; the backward
    tape records a RowSparseNDArray gradient holding only touched rows."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, stype="row_sparse",
                grad_stype="row_sparse")

    def forward(self, x):
        weight = self.weight.row_sparse_data(x)
        return nd.Embedding(x, weight, **self._kwargs,
                            sparse_grad=True)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim})".format(
            **self._kwargs)


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device BatchNorm (reference: basic_layers.py:163 +
    src/operator/contrib/sync_batch_norm.cc).

    The reference synchronizes moments with a key-based global barrier
    across GPU workers.  On TPU the equivalent is a ``psum`` over the
    data-parallel mesh axis *inside* the compiled step — which is what
    the ``_contrib_SyncBatchNorm`` operator emits when an axis name is
    bound (ops/spatial.py).  Outside a pjit/shard_map context it reduces
    over the local batch only, which is identical semantics on one chip.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices
