"""Contrib layers (reference: gluon/contrib/nn/basic_layers.py:29-208 —
Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm)."""

from __future__ import annotations

from ... import nn
from ...block import Block, HybridBlock
from .... import ndarray as nd


class Concurrent(nn.Sequential):
    """Run children on the same input, concat outputs along *axis*."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        out = [blk(x) for blk in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable :class:`Concurrent`."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [blk(x) for blk in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (useful as a Concurrent branch)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose gradient is row_sparse (reference:
    basic_layers.py:116 — sparse_grad Embedding for kvstore
    row_sparse_pull training).  Forward is a row gather; the backward
    tape records a RowSparseNDArray gradient holding only touched rows."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, stype="row_sparse",
                grad_stype="row_sparse")

    def forward(self, x):
        weight = self.weight.row_sparse_data(x)
        return nd.Embedding(x, weight, **self._kwargs,
                            sparse_grad=True)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim})".format(
            **self._kwargs)


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device BatchNorm (reference: basic_layers.py:163 +
    src/operator/contrib/sync_batch_norm.cc).

    The reference synchronizes moments with a key-based global barrier
    across GPU workers.  On TPU the equivalent is a ``psum`` over the
    data-parallel mesh axis *inside* the compiled step — which is what
    the ``_contrib_SyncBatchNorm`` operator emits when an axis name is
    bound (ops/spatial.py).  Outside a pjit/shard_map context it reduces
    over the local batch only, which is identical semantics on one chip.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class MultiHeadAttention(HybridBlock):
    """Multi-head scaled-dot-product attention over the framework's
    flash-attention operator.

    The reference predates Transformers (its transformer.cc contrib op
    is just div_sqrt_dim); this block is the TPU-native user surface
    for SURVEY §5.7 long context: q/k/v/out projections around
    ``contrib.DotProductAttention``, which lowers to the Pallas flash
    kernel on TPU and the chunked-scan path elsewhere — O(S*block)
    activation memory either way.

    Inputs/outputs are (batch, seq, units); ``num_heads`` must divide
    ``units``.  With one argument, self-attention; with three,
    cross-attention (query, key, value).
    """

    def __init__(self, units, num_heads, causal=False, use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units (%d) must be divisible by "
                             "num_heads (%d)" % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.proj_query = nn.Dense(units, use_bias=use_bias,
                                       flatten=False, prefix="query_")
            self.proj_key = nn.Dense(units, use_bias=use_bias,
                                     flatten=False, prefix="key_")
            self.proj_value = nn.Dense(units, use_bias=use_bias,
                                       flatten=False, prefix="value_")
            self.proj_out = nn.Dense(units, use_bias=use_bias,
                                     flatten=False, prefix="out_")

    def _split(self, F, x):
        # (B, S, U) -> (B, H, S, U/H)
        x = F.Reshape(x, shape=(0, 0, self._num_heads, -1))
        return F.transpose(x, axes=(0, 2, 1, 3))

    def hybrid_forward(self, F, query, key=None, value=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split(F, self.proj_query(query))
        k = self._split(F, self.proj_key(key))
        v = self._split(F, self.proj_value(value))
        att = F.contrib.DotProductAttention(q, k, v,
                                            causal=self._causal)
        # (B, H, S, d) -> (B, S, U)
        att = F.transpose(att, axes=(0, 2, 1, 3))
        att = F.Reshape(att, shape=(0, 0, -1))
        return self.proj_out(att)

    def __repr__(self):
        return "MultiHeadAttention(units=%d, heads=%d, causal=%s)" % (
            self._units, self._num_heads, self._causal)


class MoEFFN(HybridBlock):
    """Top-1 capacity-routed mixture-of-experts feed-forward layer over
    the ``_contrib_MoEFFN`` op (GShard einsum formulation).

    The reference has no MoE; this is the expert-parallel TPU extension
    at the USER level: dispatch/combine are static-shape einsums, so a
    ``ParallelTrainer(param_specs={r"expert_w": P("ep", None, None)})``
    shards the expert weights (and their optimizer state) over an
    ``ep`` mesh axis and XLA's SPMD partitioner inserts the token
    all-to-alls inside the compiled step — the trainer-level peer of
    ``parallel.moe_apply``'s explicit shard_map dispatch.

    Input/output: (batch, in_units) tokens (flatten sequences first).
    """

    def __init__(self, in_units, hidden, num_experts,
                 capacity_factor=1.0, act_type="relu", **kwargs):
        super().__init__(**kwargs)
        self._cf = float(capacity_factor)
        self._act = act_type
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(in_units, num_experts))
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, in_units, hidden))
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden, in_units))

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_w2):
        return F._contrib_MoEFFN(x, gate_weight, expert_w1, expert_w2,
                                 capacity_factor=self._cf,
                                 act_type=self._act)
