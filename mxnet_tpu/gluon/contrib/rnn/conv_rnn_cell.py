"""Convolutional recurrent cells (reference:
gluon/contrib/rnn/conv_rnn_cell.py — Conv{1,2,3}D{RNN,LSTM,GRU}Cell).

Gates are computed by two convolutions (input-to-hidden and
hidden-to-hidden) instead of dense projections; state layout is
(batch, channels, *spatial).
"""

from __future__ import annotations

from ...rnn.rnn_cell import HybridRecurrentCell


def _to_tuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvGateCell(HybridRecurrentCell):
    """Shared conv-gate plumbing: i2h/h2h convolutions over spatial
    states (reference: _BaseConvRNNCell, conv_rnn_cell.py:37)."""

    def __init__(self, input_shape, hidden_channels, gates, dims,
                 i2h_kernel, h2h_kernel, i2h_pad=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout="NCHW", activation="tanh", **kwargs):
        super().__init__(**kwargs)
        self._dims = dims
        self._input_shape = tuple(input_shape)  # (C, *spatial)
        self._hidden_channels = hidden_channels
        self._activation = activation
        self._i2h_kernel = _to_tuple(i2h_kernel, dims)
        self._h2h_kernel = _to_tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    "h2h_kernel dimensions must be odd so the state "
                    "shape is preserved (got %r)" % (self._h2h_kernel,))
        self._i2h_pad = _to_tuple(i2h_pad, dims)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        in_c = self._input_shape[0]
        g = gates
        # spatial dims of the state: input spatial + pad - kernel + 1
        self._state_shape = (hidden_channels,) + tuple(
            s + 2 * p - k + 1 for s, p, k in
            zip(self._input_shape[1:], self._i2h_pad, self._i2h_kernel))
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(g * hidden_channels, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(g * hidden_channels, hidden_channels) +
                self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(g * hidden_channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(g * hidden_channels,),
                init=h2h_bias_initializer)

    @property
    def _gates(self):
        raise NotImplementedError

    def state_info(self, batch_size=0):
        shape = (batch_size,) + self._state_shape
        return [{"shape": shape, "__layout__": "NC" + "DHW"[-self._dims:]}
                ] * self._num_states

    def _conv_gates(self, F, inputs, h, i2h_weight, h2h_weight, i2h_bias,
                    h2h_bias):
        g = self._gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=g * self._hidden_channels)
        h2h = F.Convolution(h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=g * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_ConvGateCell):
    _num_states = 1
    _gates = 1

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, inputs, states, i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMCell(_ConvGateCell):
    _num_states = 2
    _gates = 4

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, inputs, h, i2h_weight, h2h_weight,
                                    i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = F.Activation(sl[2], act_type=self._activation)
        o = F.Activation(sl[3], act_type="sigmoid")
        nc = f * c + i * g
        nh = o * F.Activation(nc, act_type=self._activation)
        return nh, [nh, nc]


class _ConvGRUCell(_ConvGateCell):
    _num_states = 1
    _gates = 3

    def hybrid_forward(self, F, inputs, h, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, inputs, h, i2h_weight, h2h_weight,
                                    i2h_bias, h2h_bias)
        xi = F.SliceChannel(i2h, num_outputs=3, axis=1)
        hi = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.Activation(xi[0] + hi[0], act_type="sigmoid")
        z = F.Activation(xi[1] + hi[1], act_type="sigmoid")
        n = F.Activation(xi[2] + r * hi[2], act_type=self._activation)
        nh = (1 - z) * n + z * h
        return nh, [nh]


def _make(cell_base, dims, name):
    class _Cell(cell_base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, **kwargs):
            super().__init__(input_shape, hidden_channels,
                             self._gates, dims, i2h_kernel, h2h_kernel,
                             i2h_pad=i2h_pad, **kwargs)
    _Cell.__name__ = name
    _Cell.__qualname__ = name
    return _Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell")
