"""Contrib cells (reference: gluon/contrib/rnn/rnn_cell.py:26
VariationalDropoutCell, :197 LSTMPCell)."""

from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell, HybridRecurrentCell
from .... import ndarray as nd


class VariationalDropoutCell(ModifierCell):
    """Locked/variational dropout: one mask per sequence, reused at
    every step, applied to inputs/states/outputs as configured."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0., **kwargs):
        super().__init__(base_cell, **kwargs)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    @staticmethod
    def _mask(arr, p):
        # Bernoulli keep-mask scaled by 1/(1-p), sampled once per
        # sequence; nd.Dropout is identity outside training mode, so
        # inference is deterministic and unmasked like the Dropout op
        return nd.Dropout(nd.ones_like(arr), p=p)

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(inputs, self.drop_inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_masks is None:
                self._state_masks = [self._mask(s, self.drop_states)
                                     for s in states]
            states = [s * m for s, m in zip(states, self._state_masks)]
        output, states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(output, self.drop_outputs)
            output = output * self._output_mask
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a projection layer on the hidden state (reference:
    rnn_cell.py:197, after the LSTMP of Sak et al. 2014)."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, r, c, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * 4)
        h2h = F.FullyConnected(r, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * 4)
        gates = i2h + h2h
        sl = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(sl[0], act_type="sigmoid")
        f = F.Activation(sl[1], act_type="sigmoid")
        g = F.Activation(sl[2], act_type="tanh")
        o = F.Activation(sl[3], act_type="sigmoid")
        nc = f * c + i * g
        hidden = o * F.Activation(nc, act_type="tanh")
        nr = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                              num_hidden=self._projection_size)
        return nr, [nr, nc]
