"""Contrib recurrent cells (reference: gluon/contrib/rnn/)."""

from .rnn_cell import VariationalDropoutCell, LSTMPCell  # noqa: F401
from .conv_rnn_cell import (Conv1DRNNCell, Conv2DRNNCell,  # noqa: F401
                            Conv3DRNNCell, Conv1DLSTMCell,
                            Conv2DLSTMCell, Conv3DLSTMCell,
                            Conv1DGRUCell, Conv2DGRUCell, Conv3DGRUCell)
