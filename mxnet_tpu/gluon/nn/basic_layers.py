"""Basic Gluon layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` (Dense:142,
Dropout:234, BatchNorm:273, Embedding:369, InstanceNorm:436, LayerNorm:532,
Lambda:616) + Sequential containers.
"""

from __future__ import annotations

from ..block import Block, HybridBlock
from ... import ndarray as nd
from ... import symbol as sym_mod

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "Flatten", "Lambda",
           "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU",
           "SELU", "Swish", "GELU"]


class Sequential(Block):
    """Stack of Blocks (reference: nn/basic_layers.py Sequential:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks (reference: HybridSequential:89)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference: Dense:142)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, no_bias=False,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense({0} -> {1}, {2})".format(
            shape[1] if shape[1] else None, shape[0],
            "linear" if self.act is None else self.act)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class BatchNorm(HybridBlock):
    """Batch normalization with moving-average aux states
    (reference: BatchNorm:273)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer,
                allow_deferred_init=True, differentiable=False)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # BN statistics stay fp32 (reference: cast)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        if F is sym_mod:
            # name the node after the parameter prefix so every BN in an
            # exported graph is unique (a bare "fwd" collides across
            # layers and breaks any by-name consumer of the JSON)
            gname = getattr(gamma, "name", "") or ""
            prefix = gname[:-len("gamma")] if gname.endswith("gamma") \
                else ""
            return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                               name=prefix + "fwd" if prefix else None,
                               **self._kwargs)
        # imperative: call the op directly and write back moving stats
        import functools
        from ...ops import registry as _reg
        from ... import autograd as _ag
        op = _reg.get_op("BatchNorm")
        training = _ag.is_training() and not \
            self._kwargs["use_global_stats"]
        mean_snap = nd.NDArray(running_mean._data)
        var_snap = nd.NDArray(running_var._data)
        raw = _reg.invoke(op, [x._data, gamma._data, beta._data,
                               running_mean._data, running_var._data],
                          dict(self._kwargs, training=training))
        out = nd.NDArray(raw[0])
        if training:
            running_mean._data = raw[3]
            running_var._data = raw[4]
        if _ag.is_recording():
            fn = functools.partial(op.fn, **dict(self._kwargs,
                                                 training=training))
            _ag.record_op(lambda *arrs: fn(*arrs)[0],
                          [x, gamma, beta, mean_snap, var_snap], [out])
        return out


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._kwargs["eps"])


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class Flatten(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
        else:
            self._func_name = None
            self._func_impl = function

    def hybrid_forward(self, F, x, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(x, *args)
        return self._func_impl(F, x, *args)


# -- activations ------------------------------------------------------------


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        if self._beta == 1.0:
            return F.Activation(x, act_type="swish")
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
