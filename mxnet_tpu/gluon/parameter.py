"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (920 LoC: Parameter with
deferred init, per-context copies, ParameterDict with prefix scoping).

TPU note: per-context replicas exist for the multi-device ``kvstore=local``
path; the ``kvstore='tpu'`` data-parallel path keeps ONE logical copy and
shards/replicates via the device mesh instead (parallel/ package).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from ..base import MXNetError, np_dtype, dtype_name
from ..context import Context, current_context, cpu
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import autograd
from .. import initializer as init_mod
from .. import symbol as sym_mod

__all__ = ["Parameter", "ParameterDict", "Constant",
           "DeferredInitializationError", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape was known."""


class Parameter:
    """A trainable weight (or state) of a Block."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = np_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None       # OrderedDict ctx -> NDArray
        self._grad = None
        self._deferred_init = None
        self._var = None

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, self.dtype)

    # -- shape -------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 in (0, s2)
                         for s1, s2 in zip(self._shape, new_shape)) and \
            len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise AssertionError(
                "Expected shape %s is incompatible with given shape %s for "
                "Parameter %s" % (str(new_shape), str(self._shape),
                                  self.name))
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
        elif self._data is not None:
            self._init_grad()

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._shape is None or any(s == 0 for s in self._shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid "
                "shape %s." % (self.name, str(self._shape)))
        self._finish_init(init, list(ctx), default_init)

    def _finish_init(self, init, ctx_list, default_init):
        data = nd.zeros(self._shape, dtype=dtype_name(self.dtype),
                        ctx=ctx_list[0])
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        initializer(init_mod.InitDesc(self.name), data)
        self._init_impl(data, ctx_list)

    def _init_impl(self, data, ctx_list):
        self._data = OrderedDict()
        for c in ctx_list:
            self._data[c] = data.copyto(nd.zeros(
                data.shape, ctx=c, dtype=dtype_name(self.dtype)))
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = OrderedDict()
        for c, d in self._data.items():
            g = nd.zeros(d.shape, ctx=c, dtype=str(d.dtype))
            self._grad[c] = g
            autograd.mark_variables([d], [g], self._grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter %s has not been initialized yet" % self.name)
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                "Parameter %s awaiting shape inference" % self.name)
        init, ctx_list, default_init = self._deferred_init
        self._finish_init(init, ctx_list, default_init)

    # -- access ------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s has not been initialized yet because "
                    "initialization was deferred. Actual initialization "
                    "happens during the first forward pass." % self.name)
            raise RuntimeError(
                "Parameter %s has not been initialized. You should "
                "initialize parameters with Block.collect_params()"
                ".initialize()" % self.name)
        if ctx is not None and ctx not in self._data:
            raise RuntimeError(
                "Parameter %s was not initialized on context %s." %
                (self.name, ctx))

    def data(self, ctx=None):
        self._check_initialized()
        if ctx is None:
            return next(iter(self._data.values()))
        self._check_initialized(Context(ctx))
        return self._data[Context(ctx)]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because "
                "grad_req='null'" % self.name)
        if ctx is None:
            return next(iter(self._grad.values()))
        return self._grad[Context(ctx)]

    def list_grad(self):
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError("grad_req='null' for Parameter %s" %
                               self.name)
        return list(self._grad.values())

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_initialized()
        return list(self._data.keys())

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is None:
                raise RuntimeError("Parameter %s has not been initialized" %
                                   self.name)
            self._finish_deferred_init()
        for c, d in self._data.items():
            arr = data.as_in_context(c) if isinstance(data, NDArray) else \
                nd.array(data, ctx=c)
            d._data = arr._data.astype(d._data.dtype)
        # re-mark variables so the tape sees the new value
        if self._grad is not None:
            for c, d in self._data.items():
                autograd.mark_variables([d], [self._grad[c]],
                                        self._grad_req)

    def row_sparse_data(self, row_id):
        # row_sparse weights: full fetch then retain (ICI all-gather path
        # is in kvstore)
        from ..ndarray import sparse as _sp
        w = self.data()
        return w

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            data = next(iter(self._data.values()))
            self._init_impl(data, ctx)
        elif self._deferred_init is not None:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, ctx, default_init)

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is None:
            return
        with autograd.pause():
            self._data = OrderedDict(
                (c, d.astype(dtype)) for c, d in self._data.items())
            if self._grad is not None:
                self._grad = OrderedDict(
                    (c, g.astype(dtype)) for c, g in self._grad.items())
                for c in self._data:
                    autograd.mark_variables([self._data[c]],
                                            [self._grad[c]],
                                            self._grad_req)

    def var(self):
        if self._var is None:
            shape = self._shape if (self._shape is not None and
                                    all(s != 0 for s in self._shape)) \
                else None
            self._var = sym_mod.var(self.name, shape=shape,
                                    lr_mult=self.lr_mult,
                                    wd_mult=self.wd_mult)
        return self._var


class Constant(Parameter):
    """Non-differentiable constant parameter
    (reference: parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class Init(init_mod.Initializer):
            def _init_weight(self, _, arr):
                value.copyto(arr)
        init_name = "Constant_{}_{}".format(name, id(self))
        init_mod._reg.register(Init, name=init_name)
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype), init=init_name,
                         differentiable=False)


class ParameterDict:
    """Ordered dict of Parameters with prefix scoping
    (reference: parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        return "ParameterDict %r (%d params)" % (self._prefix,
                                                 len(self._params))

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and \
                            existing is not None:
                        # merge partial shapes
                        if len(v) == len(existing):
                            merged = tuple(
                                a if a != 0 else b
                                for a, b in zip(existing, v))
                            param._shape = merged
                        continue
                    if k == "dtype":
                        continue
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("No constant named %r" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("Cannot update self with other because "
                                 "they have different Parameters with the "
                                 "same name %r" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError("Prefix %r is to be striped before saving, "
                                 "but Parameter %r does not start with it" %
                                 (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = {restore_prefix + k: v
                    for k, v in nd.load(filename).items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise IOError("Parameter %r is missing in file %r" %
                                  (name, filename))
        for name, arr in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError("Parameter %r loaded from file %r is not "
                                  "present in this ParameterDict" %
                                  (name, filename))
                continue
            param = self[name]
            if param._data is None and param._deferred_init is not None:
                param.shape = arr.shape
                param._finish_deferred_init()
            elif param._data is None:
                param._shape = arr.shape
                param.initialize(ctx=ctx or cpu())
            param.set_data(arr)
