"""Multi-host data-parallel training over jax.distributed.

Launch N worker processes on one machine with the reference-style
launcher (no parameter servers — the gradient all-reduce is in-graph):

    python tools/launch.py -n 2 -s 0 -- \
        python examples/train_multihost.py

Each process joins the coordinator (bootstrapped from the DMLC_* env
the launcher sets), builds ONE global mesh over every process's
devices, and feeds only its own shard of each batch; XLA routes the
gradient psum over ICI/DCN.  On real multi-host TPU slices the same
script runs unchanged — the launcher (or GKE/..) just starts one
process per host.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-steps", type=int, default=30)
    parser.add_argument("--global-batch", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args(argv)

    # single-host CPU testing: give each process a few virtual devices
    if "XLA_FLAGS" not in os.environ:
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=4"

    from mxnet_tpu.parallel import multihost
    if not multihost.init_multihost():
        print("train_multihost: single process (set DMLC_NUM_WORKER "
              "via tools/launch.py -n N -s 0); continuing standalone")
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    rank = multihost.process_index()
    nproc = multihost.process_count()
    mesh = multihost.global_mesh({"dp": -1})

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    trainer = ParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
        mesh=mesh)

    # every process generates the SAME global synthetic problem (same
    # seed) and feeds its own contiguous shard of each batch
    rs = np.random.RandomState(0)
    w_true = rs.randn(32, 10).astype(np.float32)
    local_b = args.global_batch // nproc
    lo = rank * local_b

    first = last = None
    for step in range(args.num_steps):
        xg = rs.randn(args.global_batch, 32).astype(np.float32)
        yg = (xg @ w_true).argmax(1).astype(np.float32)
        x = mx.nd.array(xg[lo:lo + local_b])
        y = mx.nd.array(yg[lo:lo + local_b])
        loss = float(np.asarray(trainer.fit_batch(x, y)))
        last = loss
        if first is None:
            first = loss
        if step % 10 == 0 and rank == 0:
            print("step %3d  loss %.4f" % (step, loss), flush=True)
    print("rank %d/%d  first %.4f  last %.4f" % (rank, nproc, first,
                                                 last), flush=True)
    assert last < first, "loss did not decrease"
    print("MULTIHOST-TRAIN-OK rank %d" % rank, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
