#!/usr/bin/env python
"""Train an ImageNet-class CNN (ResNet-50 default).

Reference: ``example/image-classification/train_imagenet.py`` (data via
ImageRecordIter, symbols from the model zoo, common/fit.py loop; its
``--benchmark 1`` mode trains on synthetic data, which is also the
default here when no .rec files are given).

Two trainer paths:
  --trainer module    symbolic Module.fit (reference flow; kvstore=local/
                      dist_sync/dist_async)
  --trainer parallel  one pjit-compiled sharded train step over the
                      device mesh (kvstore='tpu' north-star path:
                      bf16 compute + f32 masters + LARS)
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
import common  # noqa: E402


def build_symbol(args):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                  magnitude=2))
    x = mx.nd.zeros((2, 3, args.image_shape, args.image_shape))
    net(x)  # materialize deferred shapes
    data = mx.sym.var("data")
    out = net(data)
    sym = mx.sym.SoftmaxOutput(data=out, name="softmax")
    params = {p.name: p for p in net.collect_params().values()}
    arg_names = [a for a in sym.list_arguments() if a != "data" and
                 a != "softmax_label"]
    aux_names = sym.list_auxiliary_states()
    arg_params = {n: params[n].data() for n in arg_names}
    aux_params = {n: params[n].data() for n in aux_names}
    return net, sym, arg_params, aux_params


def get_iters(args, kv):
    import mxnet_tpu as mx
    rank = kv.rank if kv is not None else 0
    nworker = kv.num_workers if kv is not None else 1
    shape = (3, args.image_shape, args.image_shape)
    if args.data_train and os.path.exists(args.data_train):
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True,
            num_parts=nworker, part_index=rank,
            preprocess_threads=args.data_nthreads)
        val = None
        if args.data_val and os.path.exists(args.data_val):
            val = mx.io.ImageRecordIter(
                path_imgrec=args.data_val, data_shape=shape,
                batch_size=args.batch_size, shuffle=False,
                preprocess_threads=args.data_nthreads)
        return train, val
    # synthetic benchmark mode (reference --benchmark 1)
    rng = np.random.RandomState(42 + rank)
    n = args.num_examples
    x = rng.uniform(-1, 1, (n,) + shape).astype(np.float32)
    y = rng.randint(0, args.num_classes, (n,)).astype(np.float32)
    train = mx.io.NDArrayIter(data=x, label=y,
                              batch_size=args.batch_size, shuffle=False,
                              label_name="softmax_label")
    return train, None


def fit_parallel(args):
    """kvstore='tpu' path: whole train step as one pjit program."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    net = vision.get_model(args.network, classes=args.num_classes)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh()
    trainer = ParallelTrainer(
        net, loss, optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr,
                          "momentum": args.mom, "wd": args.wd,
                          "eta": 0.001},
        mesh=mesh, multi_precision=args.dtype == "bfloat16",
        shard_params=args.zero1, remat=args.remat or None)
    train, _ = get_iters(args, None)
    logging.info("parallel trainer: mesh=%s dtype=%s", mesh, args.dtype)
    step = 0
    tic = time.time()
    for epoch in range(args.num_epochs):
        train.reset()
        for batch in train:
            l = trainer.fit_batch(batch.data[0], batch.label[0])
            step += 1
            if step % args.disp_batches == 0:
                l = float(np.asarray(l))  # forced sync (axon tunnel)
                dt = time.time() - tic
                logging.info(
                    "Epoch[%d] Batch [%d] Speed: %.2f samples/sec "
                    "loss=%.4f", epoch, step,
                    args.disp_batches * args.batch_size / dt, l)
                tic = time.time()
    return trainer


def main():
    parser = argparse.ArgumentParser(description="train imagenet")
    parser.set_defaults(network="resnet50_v1", num_epochs=1,
                        batch_size=128, lr=0.1, disp_batches=10,
                        optimizer="sgd")
    common.add_fit_args(parser)
    parser.add_argument("--trainer", default="module",
                        choices=["module", "parallel"])
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=1280)
    parser.add_argument("--image-shape", type=int, default=224)
    parser.add_argument("--data-train", type=str, default=None,
                        help="train .rec path (synthetic data if absent)")
    parser.add_argument("--data-val", type=str, default=None)
    parser.add_argument("--data-nthreads", type=int, default=4)
    parser.add_argument("--dtype", type=str, default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--zero1", action="store_true",
                        help="ZeRO-1 shard params/optimizer over dp")
    parser.add_argument("--remat", default="",
                        choices=["", "dots", "full"],
                        help="rematerialization policy for the step")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)
    if args.trainer == "parallel":
        fit_parallel(args)
        return 0

    _, sym, arg_params, aux_params = build_symbol(args)
    common.fit(args, sym, get_iters,
               arg_params=arg_params, aux_params=aux_params,
               allow_missing=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
