#!/usr/bin/env python
"""Train a small SSD-style detector (reference: example/ssd/train.py —
the SSD BASELINE config: MultiBox ops + detection data path).

With no VOC/COCO data on disk this builds a deterministic synthetic
detection set — colored rectangles on noise, one box+class per image —
so the full pipeline (ImageDetIter-style batching -> conv backbone ->
MultiBoxPrior anchors -> MultiBoxTarget matching -> cls+loc losses ->
MultiBoxDetection + NMS decode) trains and evaluates offline.

Run:  python examples/train_ssd.py --num-epochs 5
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))


def synthetic_detection_set(n, size=64, classes=3, seed=7):
    """Images with one axis-aligned colored rectangle each; label rows
    are [class_id, xmin, ymin, xmax, ymax] in [0,1] (the detection
    label layout ImageDetIter produces)."""
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3, size, size).astype(np.float32) * 0.2
    Y = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        cls = rng.randint(classes)
        w, h = rng.randint(size // 4, size // 2, 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        # class encodes which channel lights up
        X[i, cls, y0:y0 + h, x0:x0 + w] += 0.8
        Y[i, 0] = [cls, x0 / size, y0 / size, (x0 + w) / size,
                   (y0 + h) / size]
    return X, Y


def build_ssd(num_classes, num_anchors):
    """Tiny single-scale SSD head over a 3-conv backbone."""
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    label = mx.sym.var("label")
    x = data
    for i, f in enumerate((16, 32, 64)):
        x = mx.sym.Convolution(x, num_filter=f, kernel=(3, 3),
                               stride=(2, 2), pad=(1, 1),
                               name="conv%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
    # feature map: (B, 64, 8, 8)
    cls_pred = mx.sym.Convolution(
        x, num_filter=num_anchors * (num_classes + 1), kernel=(3, 3),
        pad=(1, 1), name="cls_pred")
    loc_pred = mx.sym.Convolution(
        x, num_filter=num_anchors * 4, kernel=(3, 3), pad=(1, 1),
        name="loc_pred")
    anchors = mx.sym.MultiBoxPrior(
        x, sizes=(0.3, 0.5), ratios=(1.0, 2.0, 0.5), name="anchors")
    # (B, A*(C+1), H, W) -> (B, A*H*W, C+1)
    cls_pred = mx.sym.transpose(cls_pred, (0, 2, 3, 1))
    cls_pred = mx.sym.Reshape(cls_pred, (0, -1, num_classes + 1))
    loc_pred = mx.sym.transpose(loc_pred, (0, 2, 3, 1))
    loc_pred = mx.sym.Flatten(loc_pred)
    cls_prob = mx.sym.transpose(cls_pred, (0, 2, 1))
    tgt_loc, tgt_mask, tgt_cls = mx.sym.MultiBoxTarget(
        anchors, label, cls_prob, name="target")
    # losses: softmax CE on anchor classes + smooth-L1 on offsets
    cls_loss = mx.sym.SoftmaxOutput(
        mx.sym.Reshape(cls_pred, (-1, num_classes + 1)),
        mx.sym.Reshape(tgt_cls, (-1,)),
        ignore_label=-1, use_ignore=True, normalization="valid",
        name="cls_prob")
    loc_diff = (loc_pred - tgt_loc) * tgt_mask
    loc_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(loc_diff, scalar=1.0), name="loc_loss")
    return mx.sym.Group([cls_loss, loc_loss,
                         mx.sym.BlockGrad(anchors),
                         mx.sym.BlockGrad(tgt_cls),
                         mx.sym.BlockGrad(loc_pred)])


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-examples", type=int, default=256)
    parser.add_argument("--num-classes", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args(argv)

    import mxnet_tpu as mx

    X, Y = synthetic_detection_set(args.num_examples,
                                   classes=args.num_classes)
    # MultiBoxPrior emits (sizes + ratios - 1) anchors per position
    num_anchors = 2 + 3 - 1

    net = build_ssd(args.num_classes, num_anchors)
    it = mx.io.NDArrayIter({"data": X}, {"label": Y},
                           batch_size=args.batch_size)

    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=[mx.current_context()])
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})

    first_loss = last_loss = None
    for epoch in range(args.num_epochs):
        it.reset()
        tot, n = 0.0, 0
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            outs = mod.get_outputs()
            cls_prob = outs[0].asnumpy()       # (B*A, C+1)
            tgt_cls = outs[3].asnumpy().ravel()  # (B*A,)
            valid = tgt_cls >= 0
            p = cls_prob[np.arange(len(tgt_cls)), tgt_cls.astype(int)]
            ce = -np.log(np.clip(p[valid], 1e-9, 1.0)).mean()
            loc = float(outs[1].asnumpy().mean())
            tot += ce + loc
            n += 1
        avg = tot / n
        if first_loss is None:
            first_loss = avg
        last_loss = avg
        print("epoch %d  loss %.4f" % (epoch, avg), flush=True)

    print("first %.4f -> last %.4f" % (first_loss, last_loss))
    assert last_loss < first_loss, "SSD loss did not improve"

    # decode: MultiBoxDetection + NMS end-to-end on one batch
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    outs = mod.get_outputs()
    cls_prob = outs[0].asnumpy()
    anchors = outs[2].asnumpy()
    loc_pred = outs[4].asnumpy()          # the trained loc head
    B = args.batch_size
    A = anchors.shape[1]
    probs = cls_prob.reshape(B, A, args.num_classes + 1)
    probs = np.transpose(probs, (0, 2, 1))
    det = mx.nd.MultiBoxDetection(
        mx.nd.array(probs), mx.nd.array(loc_pred),
        mx.nd.array(anchors), nms_threshold=0.5, threshold=0.01)
    det_np = det.asnumpy()
    # sanity: decode produced at least one confident detection per image
    found = (det_np[:, :, 0] >= 0).any(axis=1).mean()
    print("detections:", det.shape, "images with detections: %.2f" % found)
    return 0


if __name__ == "__main__":
    sys.exit(main())
