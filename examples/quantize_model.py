"""INT8 quantization walkthrough (reference:
example/quantization/imagenet_gen_qsym.py + imagenet_inference.py).

Trains a small FP32 convnet on synthetic data, quantizes it with each
calibration mode, and compares INT8 vs FP32 accuracy — the complete
quantize_model flow: graph rewrite, offline weight quantization,
activation calibration (naive min/max or KL-entropy), INT8 inference.

    JAX_PLATFORMS=cpu python examples/quantize_model.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx                                   # noqa: E402
from mxnet_tpu import nd                                 # noqa: E402
from mxnet_tpu.contrib.quantization import quantize_model  # noqa: E402
from mxnet_tpu.io import NDArrayIter, DataBatch          # noqa: E402


def build_net():
    d = mx.sym.Variable("data")
    x = mx.sym.Convolution(d, num_filter=8, kernel=(3, 3), pad=(1, 1),
                           name="c1")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = mx.sym.Convolution(x, num_filter=16, kernel=(3, 3), pad=(1, 1),
                           name="c2")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=10, name="fc")
    return mx.sym.SoftmaxOutput(x, mx.sym.Variable("softmax_label"),
                                name="softmax")


def main():
    rs = np.random.RandomState(0)
    n, shape = 256, (3, 16, 16)
    # synthetic 10-class problem with a linearly separable signal
    w_sig = rs.randn(int(np.prod(shape)), 10).astype(np.float32)
    xs = rs.randn(n, *shape).astype(np.float32)
    ys = (xs.reshape(n, -1) @ w_sig).argmax(1).astype(np.float32)

    sym = build_net()
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    it = NDArrayIter(xs, ys, batch_size=32, shuffle=False)
    mod.fit(it, num_epoch=40, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3}, eval_metric="acc")
    fp32_acc = dict(mod.score(it, "acc"))["accuracy"]

    arg_params, aux_params = mod.get_params()
    calib = NDArrayIter(xs[:64], None, batch_size=32)
    for mode in ("none", "naive", "entropy"):
        qsym, qargs, qaux = quantize_model(
            sym, arg_params, aux_params,
            excluded_sym_names=("fc",),      # keep the head in fp32
            calib_mode=mode,
            calib_data=None if mode == "none" else calib,
            num_calib_examples=64)
        # quantized weight shapes are parameters, not inferrable from
        # the data shape — bind the executor with them directly
        exe = qsym.bind(args={**qargs, "data": nd.zeros((32,) + shape),
                              "softmax_label": nd.zeros((32,))},
                        aux_states=qaux)
        hits = 0
        for start in range(0, n, 32):
            batch = nd.array(xs[start:start + 32])
            out = exe.forward(is_train=False, data=batch)[0].asnumpy()
            hits += int((out.argmax(1) == ys[start:start + 32]).sum())
        int8_acc = hits / n
        drop = fp32_acc - int8_acc
        print("calib=%-7s  fp32 %.3f  int8 %.3f  drop %.3f"
              % (mode, fp32_acc, int8_acc, drop))
        assert drop < 0.05, "INT8 accuracy collapsed (mode=%s)" % mode
    print("QUANTIZE-EXAMPLE-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
