#!/usr/bin/env python
"""Train a mixture-of-experts classifier with expert parallelism.

The reference has no MoE; this is the expert-parallel TPU extension
end to end: ``gluon.contrib.nn.MoEFFN`` (GShard einsum top-1 capacity
routing, ``_contrib_MoEFFN``) trained through ``ParallelTrainer`` with
the expert weights and their optimizer state sharded ``P('ep')`` over
a ``dp x ep`` mesh — XLA inserts the token all-to-alls inside the
compiled step.

The task is expert-shaped on purpose: each class lives in a different
region of input space, so a router that specializes experts beats any
single expert of the same width.  (For large-scale training add the
Switch load-balancing term via the op's ``output_aux_loss=True``
second output; this small task converges without it.)  Runs fully
offline:

    python examples/train_moe.py --num-epochs 30
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))


def synthetic_clusters(n=512, dim=16, classes=8, seed=3):
    """Gaussian clusters at random centers, one per class."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype(np.float32) * 2.0
    y = rng.randint(0, classes, n)
    x = centers[y] + 0.4 * rng.randn(n, dim).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-experts", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--min-accuracy", type=float, default=0.9)
    args = ap.parse_args(argv)

    import jax
    from jax.sharding import PartitionSpec as P
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.nn import MoEFFN
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    dim, classes = 16, 8
    X, Y = synthetic_clusters(dim=dim, classes=classes)

    net = nn.HybridSequential()
    net.add(MoEFFN(dim, args.hidden, args.num_experts,
                   capacity_factor=2.0, prefix="moe_"),
            nn.Dense(classes, prefix="head_"))
    net.initialize()
    net(mx.nd.array(X[:2]))

    # shard experts over an ep axis of num_experts when it divides the
    # device count (dp gets the rest); otherwise run without ep
    n_dev = len(jax.devices())
    ep = args.num_experts if n_dev % args.num_experts == 0 else 1
    mesh = make_mesh({"dp": n_dev // ep, "ep": ep})
    trainer = ParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="adam",
        optimizer_params={"learning_rate": 5e-3}, mesh=mesh,
        param_specs={r"expert_w": P("ep", None, None)})

    n = len(X)
    bs = args.batch_size
    for epoch in range(args.num_epochs):
        order = np.random.RandomState(epoch).permutation(n)
        losses = []
        for i in range(0, n - bs + 1, bs):
            sel = order[i:i + bs]
            losses.append(float(trainer.fit_batch(X[sel], Y[sel])))
        if epoch % 5 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d loss %.4f" % (epoch, np.mean(losses)))

    preds = np.asarray(trainer.predict_batch(X[: (n // bs) * bs]))
    acc = float((preds.argmax(-1) == Y[: len(preds)]).mean())
    print("accuracy %.3f" % acc)
    if acc < args.min_accuracy:
        print("FAILED: accuracy %.3f < %.3f" % (acc, args.min_accuracy))
        return 1
    print("MOE-TRAIN-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
