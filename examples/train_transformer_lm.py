#!/usr/bin/env python
"""Train a small causal transformer LM with the long-context attention
stack (SURVEY §5.7 TPU stance: flash/blockwise attention as one op;
ring attention for sequence parallelism).

The reference predates Transformers — this example documents the
TPU-native extension surface: ``nd.contrib.DotProductAttention`` (Pallas
flash kernel on TPU, chunked scan elsewhere) inside a Gluon block, and
``--sequence-parallel`` running the same model's attention through
``parallel.sequence_parallel_attention`` over an ``sp`` mesh axis
(needs >=2 devices, e.g. the virtual CPU mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=8``).

Data is a synthetic copy task (predict the token seen k steps ago) so
the script runs offline and the attention mechanism is actually load-
bearing: the model must attend k positions back to win.

Run:  python examples/train_transformer_lm.py --num-steps 150
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))


def copy_task_batch(rng, batch, seq, vocab, lag):
    """x[t] must predict x[t - lag] (needs attention, not just local)."""
    x = rng.randint(2, vocab, (batch, seq)).astype(np.float32)
    y = np.roll(x, lag, axis=1)
    y[:, :lag] = 1  # BOS-ish filler for the first lag positions
    return x, y


class TransformerBlock:
    """One pre-norm block: attention + MLP, parameters via Gluon."""

    def __init__(self, mx, dim, heads, prefix):
        gluon = mx.gluon
        self.mx = mx
        self.heads = heads
        self.dim = dim
        self.qkv = gluon.nn.Dense(3 * dim, use_bias=False, flatten=False,
                                  prefix=prefix + "qkv_")
        self.proj = gluon.nn.Dense(dim, use_bias=False, flatten=False,
                                   prefix=prefix + "proj_")
        self.fc1 = gluon.nn.Dense(4 * dim, activation="relu",
                                  flatten=False, prefix=prefix + "fc1_")
        self.fc2 = gluon.nn.Dense(dim, flatten=False,
                                  prefix=prefix + "fc2_")
        self.ln1 = gluon.nn.LayerNorm(prefix=prefix + "ln1_")
        self.ln2 = gluon.nn.LayerNorm(prefix=prefix + "ln2_")
        self.blocks = [self.qkv, self.proj, self.fc1, self.fc2,
                       self.ln1, self.ln2]

    def __call__(self, x, attention_fn):
        mx = self.mx
        B, S, D = x.shape
        h = self.ln1(x)
        qkv = self.qkv(h)                                  # (B,S,3D)
        qkv = mx.nd.reshape(qkv, (0, 0, 3, self.heads, D // self.heads))
        qkv = mx.nd.transpose(qkv, (2, 0, 3, 1, 4))        # (3,B,H,S,dh)
        o = attention_fn(qkv[0], qkv[1], qkv[2])           # (B,H,S,dh)
        o = mx.nd.reshape(mx.nd.transpose(o, (0, 2, 1, 3)), (0, 0, -1))
        x = x + self.proj(o)
        return x + self.fc2(self.fc1(self.ln2(x)))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-steps", type=int, default=150)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--lag", type=int, default=7)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--sequence-parallel", action="store_true",
                        help="run attention as ring attention over an "
                             "sp mesh axis (needs >= 2 devices)")
    args = parser.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    if args.sequence_parallel:
        import jax
        from mxnet_tpu.parallel import (make_mesh,
                                        sequence_parallel_attention)
        n_dev = len(jax.devices())
        if n_dev < 2:
            print("--sequence-parallel needs >=2 devices; have %d"
                  % n_dev)
            return 2
        mesh = make_mesh({"sp": n_dev})

        class RingAttention(autograd.Function):
            """Tape the shard_map ring attention: forward stores the
            jax VJP, backward replays it — grads flow through the ring
            (ppermute is differentiable)."""

            def forward(self, q, k, v):
                out, vjp = jax.vjp(
                    lambda a, b, c: sequence_parallel_attention(
                        a, b, c, mesh, axis="sp", causal=True),
                    q._data, k._data, v._data)
                self._vjp = vjp
                self._out_sharding = out.sharding
                self._dev = list(q._data.devices())[0]
                # downstream imperative ops run on the original device
                return mx.nd.NDArray(jax.device_put(out, self._dev))

            def backward(self, dout):
                cot = jax.device_put(dout._data, self._out_sharding)
                dq, dk, dv = self._vjp(cot)
                return tuple(
                    mx.nd.NDArray(jax.device_put(g, self._dev))
                    for g in (dq, dk, dv))

        def attention_fn(q, k, v):
            return RingAttention()(q, k, v)
    else:
        def attention_fn(q, k, v):
            return mx.nd.contrib.DotProductAttention(q, k, v, causal=True)

    embed = gluon.nn.Embedding(args.vocab, args.dim)
    blocks = [TransformerBlock(mx, args.dim, args.heads, "blk%d_" % i)
              for i in range(args.layers)]
    head = gluon.nn.Dense(args.vocab, flatten=False, prefix="head_")
    # positional embedding parameter
    pos = gluon.Parameter("pos_embed", shape=(1, args.seq_len, args.dim))

    all_blocks = [embed, head] + [b for blk in blocks
                                  for b in blk.blocks]
    for b in all_blocks:
        b.initialize(mx.init.Xavier())
    pos.initialize(mx.init.Normal(0.02))

    params = {}
    for b in all_blocks:
        params.update(b.collect_params())
    params[pos.name] = pos
    trainer = gluon.Trainer(params, "adam",
                            {"learning_rate": args.lr})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    first = last = None
    for step in range(args.num_steps):
        xb, yb = copy_task_batch(rng, args.batch_size, args.seq_len,
                                 args.vocab, args.lag)
        x, y = mx.nd.array(xb), mx.nd.array(yb)
        with autograd.record():
            h = embed(x) + pos.data()
            for blk in blocks:
                h = blk(h, attention_fn)
            logits = head(h)
            L = mx.nd.mean(lossfn(
                mx.nd.reshape(logits, (-1, args.vocab)),
                mx.nd.reshape(y, (-1,))))
        L.backward()
        trainer.step(1)
        lv = float(L.asnumpy())
        if first is None:
            first = lv
        last = lv
        if step % 25 == 0:
            print("step %d  loss %.4f" % (step, lv), flush=True)

    print("first %.4f -> last %.4f" % (first, last))
    assert last < first * 0.7, "transformer LM did not learn"
    print("TRANSFORMER-LM-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
