#!/usr/bin/env python
"""Sparse linear classification on LibSVM data.

Reference: ``example/sparse/linear_classification/train.py`` — a linear
model over CSR feature batches, row-sparse weight gradients, and (in
dist mode) ``kv.row_sparse_pull`` of only the active feature rows.

TPU-native mapping: the CSR x dense dot runs sparsely
(``sparse.dot`` lowers to gather + segment_sum HLO); the weight gradient
is csr^T x dlogits, computed directly in row-sparse form (only features
present in the batch produce rows); updates use the lazy row-wise SGD
kernel so untouched feature rows are never read or written.

With no dataset on disk a synthetic sparse classification problem is
generated (deterministic), so the script runs fully offline:

    python examples/train_sparse_linear.py
    python tools/launch.py -n 2 -- python examples/train_sparse_linear.py \
        --kv-store dist_sync
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))


def make_synthetic_libsvm(path, num_examples=2000, num_features=1000,
                          nnz_per_row=12, seed=7):
    """Sparse binary classification: y = sign(w_true . x)."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(num_features)
    with open(path, "w") as f:
        for _ in range(num_examples):
            idx = np.sort(rng.choice(num_features, nnz_per_row,
                                     replace=False))
            val = rng.randn(nnz_per_row)
            y = 1.0 if float(w_true[idx] @ val) > 0 else 0.0
            toks = " ".join("%d:%.5f" % (i, v) for i, v in zip(idx, val))
            f.write("%g %s\n" % (y, toks))


def main():
    parser = argparse.ArgumentParser(
        description="sparse linear classification")
    parser.add_argument("--data", type=str, default=None,
                        help="LibSVM file (synthetic if absent)")
    parser.add_argument("--num-features", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=2000)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--kv-store", type=str, default=None)
    parser.add_argument("--optimizer", type=str, default="adagrad",
                        choices=["sgd", "adagrad"])
    parser.add_argument("--min-accuracy", type=float, default=None)
    args = parser.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray import sparse as sp

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)-15s Node[" +
               os.environ.get("DMLC_WORKER_RANK", "0") + "] %(message)s")

    kv = mx.kv.create(args.kv_store) if args.kv_store and \
        "dist" in args.kv_store else None
    rank = kv.rank if kv is not None else 0
    nworker = kv.num_workers if kv is not None else 1

    path = args.data
    if path is None or not os.path.exists(path):
        path = "/tmp/sparse_linear_%d.libsvm" % os.getpid()
        if args.data:
            path = args.data
        make_synthetic_libsvm(path, args.num_examples, args.num_features)

    it = mx.io.LibSVMIter(data_libsvm=path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size)

    # dense weight + bias; gradient is row-sparse over active features
    rng = np.random.RandomState(0)
    weight = nd.array(np.zeros((args.num_features, 1), np.float32))
    bias = nd.array(np.zeros((1,), np.float32))
    opt = mx.optimizer.create(args.optimizer, learning_rate=args.lr)
    updater = mx.optimizer.get_updater(opt)

    if kv is not None:
        kv.init("weight", weight)
        kv.init("bias", bias)
        kv.set_optimizer(opt)

    def forward(csr, w, b):
        logits = sp.dot(csr, w) + b._data  # (bs, 1), sparse gather path
        return logits

    step = 0
    for epoch in range(args.num_epochs):
        it.reset()
        n_correct = n_total = 0
        loss_sum = 0.0
        for batch in it:
            csr = batch.data[0]
            y = batch.label[0].asnumpy().reshape(-1, 1)
            if kv is not None:
                # pull only the feature rows active in this batch
                # (reference: train.py row_sparse_pull per batch)
                active = np.unique(np.asarray(csr.indices.asnumpy(),
                                              np.int64))
                if active.size:
                    pulled = sp.zeros("row_sparse", weight.shape)
                    kv.row_sparse_pull("weight", out=pulled,
                                       row_ids=nd.array(active))
                    weight._data = weight._data.at[
                        np.asarray(pulled.indices.asnumpy(),
                                   np.int64)].set(pulled.data._data)
                bfull = nd.zeros(bias.shape)
                kv.pull("bias", out=bfull)
                bias._data = bfull._data

            logits = forward(csr, weight, bias)
            z = np.asarray(logits._data)
            p = 1.0 / (1.0 + np.exp(-z))
            loss_sum += float(-(y * np.log(p + 1e-12) +
                                (1 - y) * np.log(1 - p + 1e-12)).mean())
            n_correct += int(((p > 0.5) == (y > 0.5)).sum())
            n_total += y.shape[0]

            # backward: dL/dlogits = (p - y)/bs ; dL/dw = csr^T . dlogits
            dlogits = nd.array(((p - y) / y.shape[0]).astype(np.float32))
            dw_dense = sp.dot(csr, dlogits, transpose_a=True)
            dw = sp.compress_rowsparse(dw_dense)
            db = nd.array(np.asarray(dlogits._data).sum(0))

            if kv is not None:
                kv.push("weight", dw)
                kv.push("bias", db)
            else:
                updater(0, dw, weight)
                updater(1, db, bias)
            step += 1
        acc = n_correct / max(n_total, 1)
        logging.info("Epoch[%d] loss=%.4f accuracy=%.4f", epoch,
                     loss_sum / max(step, 1), acc)

    if kv is not None:
        kv.barrier()
        full = nd.zeros(weight.shape)
        kv.pull("weight", out=full)
        weight._data = full._data

    # final score on the training set (convergence gate)
    it.reset()
    n_correct = n_total = 0
    for batch in it:
        logits = forward(batch.data[0], weight, bias)
        y = batch.label[0].asnumpy().reshape(-1, 1)
        p = np.asarray(logits._data)
        n_correct += int(((p > 0) == (y > 0.5)).sum())
        n_total += y.shape[0]
    acc = n_correct / max(n_total, 1)
    print("final train accuracy: %.4f" % acc)
    if args.min_accuracy is not None and acc < args.min_accuracy:
        print("FAILED: %.4f < %.4f" % (acc, args.min_accuracy))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
