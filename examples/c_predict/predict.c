/*
 * Standalone C consumer of the predict ABI (reference:
 * example/image-classification/predict-cpp — a C++ program driving
 * c_predict_api.h).  Demonstrates that a non-Python host can load
 * libmxtpu_predict.so and run inference.
 *
 * Build + run (after `make -C src/capi`):
 *   gcc -o predict predict.c -I../../include -L../../build \
 *       -lmxtpu_predict -Wl,-rpath,../../build
 *   ./predict model-symbol.json model-0000.params 1,3,8,8
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <mxtpu/c_predict_api.h>

static char* read_file(const char* path, long* size) {
  FILE* f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "cannot open %s\n", path); exit(1); }
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) { exit(1); }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s symbol.json params.file N,C,H,W\n", argv[0]);
    return 2;
  }
  long sym_size, param_size;
  char* sym_json = read_file(argv[1], &sym_size);
  char* params = read_file(argv[2], &param_size);

  mx_uint shape[8];
  mx_uint ndim = 0;
  char* tok = strtok(argv[3], ",");
  while (tok && ndim < 8) { shape[ndim++] = (mx_uint)atoi(tok);
                            tok = strtok(NULL, ","); }
  mx_uint indptr[2] = {0, ndim};
  const char* keys[1] = {"data"};

  PredictorHandle h = NULL;
  if (MXPredCreate(sym_json, params, (int)param_size, 1, 0, 1, keys,
                   indptr, shape, &h) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= shape[i];
  mx_float* input = (mx_float*)calloc(n, sizeof(mx_float));
  for (mx_uint i = 0; i < n; ++i) input[i] = (mx_float)(i % 7) * 0.1f;
  if (MXPredSetInput(h, "data", input, n) != 0 ||
      MXPredForward(h) != 0) {
    fprintf(stderr, "forward: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint* oshape;
  mx_uint ondim;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim) != 0) {
    fprintf(stderr, "shape: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint osize = 1;
  printf("output shape: ");
  for (mx_uint i = 0; i < ondim; ++i) {
    printf("%u ", oshape[i]);
    osize *= oshape[i];
  }
  printf("\n");
  mx_float* out = (mx_float*)malloc(osize * sizeof(mx_float));
  if (MXPredGetOutput(h, 0, out, osize) != 0) {
    fprintf(stderr, "output: %s\n", MXGetLastError());
    return 1;
  }
  printf("output[0..4]:");
  for (mx_uint i = 0; i < osize && i < 5; ++i) printf(" %f", out[i]);
  printf("\nC-PREDICT-OK\n");
  MXPredFree(h);
  free(out); free(input); free(sym_json); free(params);
  return 0;
}
