// C++-binding example: train a symbol-JSON MLP classifier end to end
// through the symbolic C ABI — load the graph with
// MXSymbolCreateFromJSON, SimpleBind it, then drive
// Forward/Backward/sgd_update from C++ with no Python in this
// translation unit (libmxtpu_nd.so embeds the runtime).
//
// This is the graph-executor analogue of train_linear.cpp (which
// drives per-op imperative calls): the reference equivalent is a
// cpp-package Module-style loop over src/c_api/c_api_executor.cc's
// SimpleBind/Forward/Backward.
//
// Build + run (from repo root, after `make -C src/capi`):
//   g++ -std=c++17 -Iinclude examples/cpp/train_symbolic.cpp \
//       -Lbuild -lmxtpu_nd -o build/train_symbolic
//   PYTHONPATH=$PWD LD_LIBRARY_PATH=build ./build/train_symbolic

#include <cmath>
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include "mxtpu/cpp/ndarray.hpp"
#include "mxtpu/cpp/symbol.hpp"

using mxtpu::Executor;
using mxtpu::NDArray;
using mxtpu::Op;
using mxtpu::Symbol;

// data -> FC(16) -> relu -> FC(3) -> SoftmaxOutput  (serialized with
// the framework's symbol JSON schema, reference nnvm graph JSON)
static const char* kMlpJson =
    R"({"nodes":[{"op":"null","name":"data","inputs":[]},)"
    R"({"op":"null","name":"fc1_weight","inputs":[]},)"
    R"({"op":"null","name":"fc1_bias","inputs":[]},)"
    R"({"op":"FullyConnected","name":"fc1","inputs":[[0,0,0],[1,0,0],[2,0,0]],"attrs":{"num_hidden":"16"}},)"
    R"({"op":"Activation","name":"relu1","inputs":[[3,0,0]],"attrs":{"act_type":"relu"}},)"
    R"({"op":"null","name":"fc2_weight","inputs":[]},)"
    R"({"op":"null","name":"fc2_bias","inputs":[]},)"
    R"({"op":"FullyConnected","name":"fc2","inputs":[[4,0,0],[5,0,0],[6,0,0]],"attrs":{"num_hidden":"3"}},)"
    R"({"op":"null","name":"softmax_label","inputs":[]},)"
    R"({"op":"SoftmaxOutput","name":"softmax","inputs":[[7,0,0],[8,0,0]]}],)"
    R"("arg_nodes":[0,1,2,5,6,8],"node_row_ptr":[0,1,2,3,4,5,6,7,8,9,10],)"
    R"("heads":[[9,0,0]],)"
    R"("attrs":{"mxnet_version":["int",10301],"framework":["str","mxnet_tpu"]}})";

int main() {
  const mx_uint kBatch = 96, kDim = 8, kClasses = 3;

  Symbol sym(kMlpJson);
  // JSON round-trip through the ABI must preserve the graph
  Symbol again(sym.ToJSON());
  if (again.ListArguments() != sym.ListArguments()) {
    std::fprintf(stderr, "tojson round-trip changed the arguments\n");
    return 1;
  }

  Executor ex(sym, {{"data", {kBatch, kDim}},
                    {"softmax_label", {kBatch}}});

  // three gaussian blobs, one per class
  std::mt19937 gen(42);
  std::normal_distribution<float> noise(0.0f, 0.6f);
  std::vector<float> xs(kBatch * kDim), ys(kBatch);
  for (mx_uint i = 0; i < kBatch; ++i) {
    int c = static_cast<int>(i % kClasses);
    ys[i] = static_cast<float>(c);
    for (mx_uint j = 0; j < kDim; ++j)
      xs[i * kDim + j] = noise(gen) + 2.0f * static_cast<float>(c == static_cast<int>(j % kClasses));
  }
  ex.Args().at("data").CopyFrom(xs.data(), xs.size() * sizeof(float));
  ex.Args().at("softmax_label").CopyFrom(ys.data(),
                                         ys.size() * sizeof(float));

  // xavier-ish init for the weights; biases stay zero
  std::uniform_real_distribution<float> unif(-0.3f, 0.3f);
  for (const char* w : {"fc1_weight", "fc2_weight"}) {
    NDArray& arr = ex.Args().at(w);
    std::vector<float> init(arr.Size());
    for (auto& v : init) v = unif(gen);
    arr.CopyFrom(init.data(), init.size() * sizeof(float));
  }

  auto ce_loss = [&](const std::vector<float>& probs) {
    double acc = 0.0;
    for (mx_uint i = 0; i < kBatch; ++i)
      acc -= std::log(std::max(
          1e-12f, probs[i * kClasses + static_cast<int>(ys[i])]));
    return static_cast<float>(acc / kBatch);
  };

  float first_loss = 0.0f, loss = 0.0f;
  for (int step = 0; step < 60; ++step) {
    ex.Forward(/*is_train=*/true);
    ex.Backward();
    for (auto& kv : ex.Grads()) {
      if (kv.first == "data" || kv.first == "softmax_label") continue;
      // aliased update: the executor sees the new weights next step.
      // SoftmaxOutput's gradient is per-sample (reference
      // normalization='null'), so normalize by batch in the optimizer
      // exactly like Module does via rescale_grad.
      Op("sgd_update").Arg(ex.Args().at(kv.first)).Arg(kv.second)
          .Set("lr", 0.5f).Set("wd", 0.0f)
          .Set("rescale_grad", 1.0f / kBatch).Invoke();
    }
    loss = ce_loss(ex.Outputs()[0].ToVector());
    if (step == 0) first_loss = loss;
  }

  // final accuracy from an inference-mode forward
  ex.Forward(/*is_train=*/false);
  auto probs = ex.Outputs()[0].ToVector();
  int correct = 0;
  for (mx_uint i = 0; i < kBatch; ++i) {
    int best = 0;
    for (mx_uint c = 1; c < kClasses; ++c)
      if (probs[i * kClasses + c] > probs[i * kClasses + best])
        best = static_cast<int>(c);
    correct += best == static_cast<int>(ys[i]);
  }
  float acc = static_cast<float>(correct) / kBatch;

  std::printf("loss %.4f -> %.4f, accuracy %.3f\n", first_loss, loss, acc);
  if (!(loss < 0.5f * first_loss) || acc < 0.9f) {
    std::fprintf(stderr, "training did not converge\n");
    return 1;
  }
  std::printf("symbolic C ABI training OK\n");
  return 0;
}
