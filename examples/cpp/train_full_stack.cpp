// C++-binding example: the COMPLETE native training stack — data from
// MXDataIterCreateIter(CSVIter), graph from MXSymbolCreateFromJSON,
// compute through MXExecutorForward/Backward, gradients synchronized
// through MXKVStorePushEx/PullEx, weights stepped with sgd_update —
// i.e. a Module-style epoch loop using every C ABI surface and no
// Python in this translation unit.
//
// The reference reaches the same loop through include/mxnet/c_api.h
// (c_api.cc MXDataIter*/MXKVStore* + c_api_executor.cc); this is the
// parity demonstration for that training path.
//
// Build + run (from repo root, after `make -C src/capi`):
//   g++ -std=c++17 -Iinclude examples/cpp/train_full_stack.cpp \
//       -Lbuild -lmxtpu_nd -o build/train_full_stack
//   PYTHONPATH=$PWD LD_LIBRARY_PATH=build ./build/train_full_stack /tmp

#include <cmath>
#include <cstdio>
#include <fstream>
#include <random>
#include <string>
#include <vector>

#include "mxtpu/cpp/ndarray.hpp"
#include "mxtpu/cpp/symbol.hpp"

using mxtpu::Check;
using mxtpu::Executor;
using mxtpu::NDArray;
using mxtpu::Op;
using mxtpu::Symbol;

// data -> FC(16) -> relu -> FC(3) -> SoftmaxOutput
static const char* kMlpJson =
    R"({"nodes":[{"op":"null","name":"data","inputs":[]},)"
    R"({"op":"null","name":"fc1_weight","inputs":[]},)"
    R"({"op":"null","name":"fc1_bias","inputs":[]},)"
    R"({"op":"FullyConnected","name":"fc1","inputs":[[0,0,0],[1,0,0],[2,0,0]],"attrs":{"num_hidden":"16"}},)"
    R"({"op":"Activation","name":"relu1","inputs":[[3,0,0]],"attrs":{"act_type":"relu"}},)"
    R"({"op":"null","name":"fc2_weight","inputs":[]},)"
    R"({"op":"null","name":"fc2_bias","inputs":[]},)"
    R"({"op":"FullyConnected","name":"fc2","inputs":[[4,0,0],[5,0,0],[6,0,0]],"attrs":{"num_hidden":"3"}},)"
    R"({"op":"null","name":"softmax_label","inputs":[]},)"
    R"({"op":"SoftmaxOutput","name":"softmax","inputs":[[7,0,0],[8,0,0]]}],)"
    R"("arg_nodes":[0,1,2,5,6,8],"node_row_ptr":[0,1,2,3,4,5,6,7,8,9,10],)"
    R"("heads":[[9,0,0]],)"
    R"("attrs":{"mxnet_version":["int",10301],"framework":["str","mxnet_tpu"]}})";

int main(int argc, char** argv) {
  const std::string tmp = argc > 1 ? argv[1] : "/tmp";
  const mx_uint kBatch = 32, kDim = 8, kClasses = 3, kRows = 96;

  // ---- synthetic CSV dataset (blobs, one per class) -----------------
  std::mt19937 gen(7);
  std::normal_distribution<float> noise(0.0f, 0.5f);
  const std::string dpath = tmp + "/fullstack_d.csv";
  const std::string lpath = tmp + "/fullstack_l.csv";
  {
    std::ofstream df(dpath), lf(lpath);
    for (mx_uint i = 0; i < kRows; ++i) {
      int c = static_cast<int>(i % kClasses);
      for (mx_uint j = 0; j < kDim; ++j)
        df << (noise(gen) +
               2.0f * (c == static_cast<int>(j % kClasses)))
           << (j + 1 < kDim ? "," : "\n");
      lf << c << "\n";
    }
  }

  // ---- data iterator through the C ABI ------------------------------
  const char* ikeys[] = {"data_csv", "label_csv", "data_shape",
                         "batch_size"};
  const std::string shape_s = "(" + std::to_string(kDim) + ",)";
  const std::string batch_s = std::to_string(kBatch);
  const char* ivals[] = {dpath.c_str(), lpath.c_str(), shape_s.c_str(),
                         batch_s.c_str()};
  DataIterHandle iter = nullptr;
  Check(MXDataIterCreateIter("CSVIter", 4, ikeys, ivals, &iter));

  // ---- bind + init ---------------------------------------------------
  Symbol sym(kMlpJson);
  Executor ex(sym, {{"data", {kBatch, kDim}},
                    {"softmax_label", {kBatch}}});
  std::uniform_real_distribution<float> unif(-0.3f, 0.3f);
  for (const char* w : {"fc1_weight", "fc2_weight"}) {
    NDArray& arr = ex.Args().at(w);
    std::vector<float> init(arr.Size());
    for (auto& v : init) v = unif(gen);
    arr.CopyFrom(init.data(), init.size() * sizeof(float));
  }

  // ---- kvstore: one key per trainable parameter ----------------------
  KVStoreHandle kv = nullptr;
  Check(MXKVStoreCreate("local", &kv));
  std::vector<std::string> pnames;
  for (auto& kvp : ex.Grads())
    if (kvp.first != "data" && kvp.first != "softmax_label")
      pnames.push_back(kvp.first);
  for (auto& n : pnames) {
    const char* k = n.c_str();
    NDArrayHandle h = ex.Args().at(n).handle();
    Check(MXKVStoreInitEx(kv, 1, &k, &h));
  }

  // ---- epoch loop ----------------------------------------------------
  float first_loss = -1.0f, loss = 0.0f;
  for (int epoch = 0; epoch < 40; ++epoch) {
    Check(MXDataIterBeforeFirst(iter));
    int has = 0;
    double ep_loss = 0.0;
    int batches = 0;
    for (;;) {
      Check(MXDataIterNext(iter, &has));
      if (!has) break;
      NDArrayHandle dh = nullptr, lh = nullptr;
      Check(MXDataIterGetData(iter, &dh));
      Check(MXDataIterGetLabel(iter, &lh));
      NDArray db = NDArray::Adopt(dh), lb = NDArray::Adopt(lh);
      // feed the batch into the bound args
      auto dv = db.ToVector();
      auto lv = lb.ToVector();
      ex.Args().at("data").CopyFrom(dv.data(),
                                    dv.size() * sizeof(float));
      ex.Args().at("softmax_label").CopyFrom(
          lv.data(), lv.size() * sizeof(float));
      ex.Forward(/*is_train=*/true);
      ex.Backward();
      // gradient "sync" through the kvstore (push grads, pull the
      // reduced value back — the reference's kvstore update shape),
      // then the fused sgd step on the pulled gradient
      for (auto& n : pnames) {
        const char* k = n.c_str();
        NDArrayHandle gh = ex.Grads().at(n).handle();
        Check(MXKVStorePushEx(kv, 1, &k, &gh, 0));
        Check(MXKVStorePullEx(kv, 1, &k, &gh, 0));
        Op("sgd_update").Arg(ex.Args().at(n)).Arg(ex.Grads().at(n))
            .Set("lr", 0.5f).Set("wd", 0.0f)
            .Set("rescale_grad", 1.0f / kBatch).Invoke();
      }
      // batch cross-entropy from the softmax output
      auto probs = ex.Outputs()[0].ToVector();
      double acc = 0.0;
      for (mx_uint i = 0; i < kBatch; ++i)
        acc -= std::log(std::max(
            1e-12f, probs[i * kClasses + static_cast<int>(lv[i])]));
      ep_loss += acc / kBatch;
      ++batches;
    }
    loss = static_cast<float>(ep_loss / batches);
    if (first_loss < 0) first_loss = loss;
  }

  Check(MXDataIterFree(iter));
  Check(MXKVStoreFree(kv));

  std::printf("loss %.4f -> %.4f\n", first_loss, loss);
  if (!(loss < 0.25f * first_loss)) {
    std::fprintf(stderr, "training did not converge\n");
    return 1;
  }
  std::printf("full-stack C ABI training OK\n");
  return 0;
}
