// C++-binding example: train a linear regressor end to end through the
// NDArray/op-invoke ABI — no Python in THIS translation unit; the
// runtime is reached through libmxtpu_nd.so (which embeds CPython).
//
// Mirrors the reference's cpp-package examples
// (cpp-package/example/*.cpp): create arrays, run forward math with
// registered ops, apply the fused sgd update, checkpoint.
//
// Build + run (from repo root, after `make -C src/capi`):
//   g++ -std=c++17 -Iinclude examples/cpp/train_linear.cpp \
//       -Lbuild -lmxtpu_nd -o build/train_linear
//   PYTHONPATH=$PWD LD_LIBRARY_PATH=build ./build/train_linear

#include <cstdio>
#include <cmath>
#include <random>
#include <string>
#include <vector>

#include "mxtpu/cpp/ndarray.hpp"

using mxtpu::NDArray;
using mxtpu::Op;

int main(int argc, char** argv) {
  // checkpoint directory from argv so concurrent runs don't race
  const std::string ckpt =
      std::string(argc > 1 ? argv[1] : "/tmp") + "/cpp_linear.params";
  const mx_uint n = 64, d = 8;
  std::mt19937 gen(0);
  std::normal_distribution<float> dist(0.0f, 1.0f);

  // synthetic y = X w* (+ tiny noise)
  std::vector<float> xs(n * d), w_true(d), ys(n);
  for (auto& v : xs) v = dist(gen);
  for (auto& v : w_true) v = dist(gen);
  for (mx_uint i = 0; i < n; ++i) {
    float acc = 0.0f;
    for (mx_uint j = 0; j < d; ++j) acc += xs[i * d + j] * w_true[j];
    ys[i] = acc + 0.01f * dist(gen);
  }

  NDArray X({n, d}, xs);
  NDArray y({n, 1}, ys);
  NDArray w({d, 1}, std::vector<float>(d, 0.0f));

  float last_loss = 0.0f;
  for (int step = 0; step < 200; ++step) {
    // pred = X @ w ; err = pred - y
    auto pred = Op("dot").Arg(X).Arg(w).Invoke();
    auto err = Op("elemwise_sub").Arg(pred[0]).Arg(y).Invoke();
    // grad = X^T err / n
    auto g = Op("dot").Arg(X).Arg(err[0])
                 .Set("transpose_a", "True").Invoke();
    auto gs = Op("_div_scalar").Arg(g[0])
                  .Set("scalar", static_cast<float>(n)).Invoke();
    // fused in-place-style update: w <- sgd(w, grad)
    auto upd = Op("sgd_update").Arg(w).Arg(gs[0])
                   .Set("lr", 0.5f).Set("wd", 0.0f).Invoke();
    w = std::move(upd[0]);
    if (step % 50 == 0 || step == 199) {
      auto sq = Op("square").Arg(err[0]).Invoke();
      auto m = Op("mean").Arg(sq[0]).Invoke();
      last_loss = m[0].ToVector()[0];
      std::printf("step %3d  mse %.6f\n", step, last_loss);
    }
  }

  // recovered weights must match the generator
  auto got = w.ToVector();
  float max_err = 0.0f;
  for (mx_uint j = 0; j < d; ++j)
    max_err = std::max(max_err, std::fabs(got[j] - w_true[j]));
  std::printf("max |w - w*| = %.4f\n", max_err);

  mxtpu::Save(ckpt, {{"w", &w}});
  auto loaded = mxtpu::Load(ckpt);
  if (loaded.at("w").ToVector() != got) {
    std::printf("CHECKPOINT-MISMATCH\n");
    return 1;
  }
  if (last_loss < 1e-3f && max_err < 0.05f) {
    std::printf("CPP-TRAIN-OK\n");
    return 0;
  }
  std::printf("CPP-TRAIN-FAILED\n");
  return 1;
}
