"""Shared fit harness for the example training scripts.

Reference: ``example/image-classification/common/fit.py`` (add_fit_args +
fit:148 — kvstore creation, lr schedule from epoch steps, Module.fit with
checkpoint/speedometer callbacks).
"""

from __future__ import annotations

import argparse
import logging
import os


def add_fit_args(parser):
    parser.add_argument("--network", type=str, default=None,
                        help="the neural network to use")
    parser.add_argument("--kv-store", type=str, default="local",
                        help="key-value store type "
                             "(local/device/tpu/dist_sync/dist_async)")
    parser.add_argument("--num-epochs", type=int, default=2,
                        help="max epochs to run")
    parser.add_argument("--lr", type=float, default=0.05,
                        help="initial learning rate")
    parser.add_argument("--lr-factor", type=float, default=0.1,
                        help="lr decay ratio")
    parser.add_argument("--lr-step-epochs", type=str, default="",
                        help="epochs at which lr decays, e.g. '30,60'")
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--mom", type=float, default=0.9,
                        help="momentum")
    parser.add_argument("--wd", type=float, default=1e-4,
                        help="weight decay")
    parser.add_argument("--batch-size", type=int, default=64,
                        help="total batch size")
    parser.add_argument("--disp-batches", type=int, default=20,
                        help="show progress every N batches")
    parser.add_argument("--model-prefix", type=str, default=None,
                        help="checkpoint prefix")
    parser.add_argument("--load-epoch", type=int, default=None,
                        help="resume from this checkpoint epoch")
    parser.add_argument("--top-k", type=int, default=0,
                        help="also report top-k accuracy")
    parser.add_argument("--monitor", type=int, default=0,
                        help="install a Monitor every N batches")
    return parser


def _lr_scheduler(args, epoch_size, kv):
    import mxnet_tpu as mx
    begin_epoch = args.load_epoch or 0
    if not args.lr_step_epochs:
        return args.lr, None
    step_epochs = [int(e) for e in args.lr_step_epochs.split(",") if e]
    lr = args.lr
    for e in step_epochs:
        if begin_epoch >= e:
            lr *= args.lr_factor
    steps = [epoch_size * (e - begin_epoch) for e in step_epochs
             if e > begin_epoch]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor)


def fit(args, network, data_loader, arg_params=None, aux_params=None,
        **kwargs):
    """Train *network* (a Symbol) with the reference fit flow:
    kvstore → lr schedule → Module.fit with callbacks.

    data_loader(args, kv) -> (train_iter, val_iter)
    """
    import mxnet_tpu as mx

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)-15s Node[" +
               os.environ.get("DMLC_WORKER_RANK", "0") + "] %(message)s")

    kv = None
    if "dist" in args.kv_store:
        kv = mx.kv.create(args.kv_store)
    train, val = data_loader(args, kv)

    epoch_size = getattr(args, "num_examples", 0) // args.batch_size \
        if getattr(args, "num_examples", 0) else 100
    if kv is not None:
        epoch_size //= max(1, kv.num_workers)
    lr, lr_sched = _lr_scheduler(args, epoch_size, kv)

    optimizer_params = {
        "learning_rate": lr,
        "rescale_grad": 1.0 / args.batch_size /
        (kv.num_workers if kv is not None else 1),
    }
    if lr_sched is not None:
        optimizer_params["lr_scheduler"] = lr_sched
    if args.optimizer in ("sgd", "nag", "signum", "lbsgd"):
        optimizer_params["momentum"] = args.mom
        optimizer_params["wd"] = args.wd

    mod = mx.mod.Module(symbol=network,
                        data_names=("data",),
                        label_names=("softmax_label",))

    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch

    eval_metrics = [mx.metric.create("accuracy")]
    if args.top_k > 0:
        eval_metrics.append(
            mx.metric.create("top_k_accuracy", top_k=args.top_k))

    batch_end = [mx.callback.Speedometer(args.batch_size,
                                         args.disp_batches)]
    epoch_end = []
    if args.model_prefix:
        epoch_end.append(mx.callback.do_checkpoint(args.model_prefix))

    mod.fit(train,
            eval_data=val,
            eval_metric=eval_metrics,
            kvstore=kv if kv is not None else args.kv_store,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params,
            aux_params=aux_params,
            begin_epoch=begin_epoch,
            num_epoch=args.num_epochs,
            batch_end_callback=batch_end,
            epoch_end_callback=epoch_end,
            **kwargs)
    return mod
