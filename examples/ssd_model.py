"""SSD-300 with the VGG16-reduced backbone — the reference's detection
headline architecture (example/ssd/symbol/{vgg16_reduced,symbol_builder}.py),
built symbolically on this framework's op set.

Six feature scales (38/19/10/5/3/1 for 300 input), per-scale class +
offset heads, `MultiBoxPrior` anchors (8732 total at the reference's
sizes/ratios), and `MultiBoxDetection` (decode + NMS) for inference.
`tools/benchmark_ssd.py` times it; `build_ssd300_train` attaches the
MultiBoxTarget + SoftmaxOutput/smooth-L1 training heads the same way
example/ssd/symbol/symbol_builder.py:training does.
"""

from __future__ import annotations

# per-scale anchor config — reference example/ssd/symbol/symbol_factory.py
# get_config('vgg16_reduced', 300)
_SIZES = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
          (0.71, 0.79), (0.88, 0.961)]
_RATIOS = [(1.0, 2.0, 0.5), (1.0, 2.0, 0.5, 3.0, 1.0 / 3),
           (1.0, 2.0, 0.5, 3.0, 1.0 / 3), (1.0, 2.0, 0.5, 3.0, 1.0 / 3),
           (1.0, 2.0, 0.5), (1.0, 2.0, 0.5)]


def _vgg16_reduced(sym, data):
    """VGG16 through conv5_3, then the SSD 'reduced' conv6 (dilated) +
    conv7 — reference example/ssd/symbol/vgg16_reduced.py."""
    x = data
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512)]
    feats = []
    for b, (n, f) in enumerate(cfg):
        for i in range(n):
            x = sym.Convolution(x, num_filter=f, kernel=(3, 3),
                                pad=(1, 1),
                                name="conv%d_%d" % (b + 1, i + 1))
            x = sym.Activation(x, act_type="relu")
        if b == 3:
            feats.append(x)       # conv4_3 -> 38x38 scale
        # ceil-mode pooling (SSD caffe heritage): 75 -> 38, not 37 —
        # required for the reference's 8732-anchor grid
        x = sym.Pooling(x, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", pooling_convention="full",
                        name="pool%d" % (b + 1))
    for i in range(3):            # conv5_1..5_3
        x = sym.Convolution(x, num_filter=512, kernel=(3, 3),
                            pad=(1, 1), name="conv5_%d" % (i + 1))
        x = sym.Activation(x, act_type="relu")
    x = sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="max", name="pool5")
    # reduced fc6/fc7: dilated 3x3 + 1x1
    x = sym.Convolution(x, num_filter=1024, kernel=(3, 3), pad=(6, 6),
                        dilate=(6, 6), name="fc6")
    x = sym.Activation(x, act_type="relu")
    x = sym.Convolution(x, num_filter=1024, kernel=(1, 1), name="fc7")
    x = sym.Activation(x, act_type="relu")
    feats.append(x)               # 19x19 scale
    # extra feature blocks: 10x10, 5x5, 3x3, 1x1
    for j, (f1, f2, s, p) in enumerate(
            [(256, 512, 2, 1), (128, 256, 2, 1),
             (128, 256, 1, 0), (128, 256, 1, 0)]):
        x = sym.Convolution(x, num_filter=f1, kernel=(1, 1),
                            name="extra%d_1x1" % j)
        x = sym.Activation(x, act_type="relu")
        x = sym.Convolution(x, num_filter=f2, kernel=(3, 3),
                            stride=(s, s), pad=(p, p),
                            name="extra%d_3x3" % j)
        x = sym.Activation(x, act_type="relu")
        feats.append(x)
    return feats


def _multibox_layers(sym, feats, num_classes):
    """Per-scale heads + anchors, concatenated over scales
    (reference symbol_builder.py multibox_layer)."""
    cls_preds, loc_preds, anchors = [], [], []
    for i, feat in enumerate(feats):
        na = len(_SIZES[i]) + len(_RATIOS[i]) - 1
        if i == 0:
            # conv4_3 features are L2-normalized with a learned scale
            # (reference vgg16_reduced.py relu4_3_scale)
            feat = sym.L2Normalization(feat, mode="channel",
                                       name="relu4_3_norm")
        cp = sym.Convolution(feat, num_filter=na * (num_classes + 1),
                             kernel=(3, 3), pad=(1, 1),
                             name="cls_pred%d" % i)
        cp = sym.transpose(cp, (0, 2, 3, 1))
        cls_preds.append(sym.Reshape(cp, (0, -1, num_classes + 1)))
        lp = sym.Convolution(feat, num_filter=na * 4, kernel=(3, 3),
                             pad=(1, 1), name="loc_pred%d" % i)
        lp = sym.transpose(lp, (0, 2, 3, 1))
        loc_preds.append(sym.Flatten(lp))
        anchors.append(sym.Reshape(
            sym.MultiBoxPrior(feat, sizes=_SIZES[i], ratios=_RATIOS[i],
                              clip=True, name="anchors%d" % i),
            (1, -1, 4)))
    cls_pred = sym.concat(*cls_preds, dim=1)    # (B, A, C+1)
    loc_pred = sym.concat(*loc_preds, dim=1)    # (B, A*4)
    anchor = sym.concat(*anchors, dim=1)        # (1, A, 4)
    return cls_pred, loc_pred, anchor


def build_ssd300_infer(num_classes=20, nms_thresh=0.45, nms_topk=400):
    """Inference graph: data0 -> (B, A, 6) [cls, score, 4 box coords]."""
    import mxnet_tpu as mx
    sym = mx.sym
    data = sym.var("data0")
    feats = _vgg16_reduced(sym, data)
    cls_pred, loc_pred, anchor = _multibox_layers(sym, feats,
                                                  num_classes)
    cls_prob = sym.transpose(
        sym.softmax(cls_pred, axis=-1), (0, 2, 1))
    return sym.MultiBoxDetection(
        cls_prob, loc_pred, anchor, nms_threshold=nms_thresh,
        nms_topk=nms_topk, name="detection")


def build_ssd300_train(num_classes=20):
    """Training graph: cls softmax (hard-negative-mined targets) +
    smooth-L1 on offsets, mirroring symbol_builder.py's heads."""
    import mxnet_tpu as mx
    sym = mx.sym
    data = sym.var("data0")
    label = sym.var("label")
    feats = _vgg16_reduced(sym, data)
    cls_pred, loc_pred, anchor = _multibox_layers(sym, feats,
                                                  num_classes)
    cls_prob_t = sym.transpose(
        sym.softmax(cls_pred, axis=-1), (0, 2, 1))
    tgt_loc, tgt_mask, tgt_cls = sym.MultiBoxTarget(
        anchor, label, cls_prob_t, name="target")
    cls_loss = sym.SoftmaxOutput(
        sym.Reshape(cls_pred, (-1, num_classes + 1)),
        sym.Reshape(tgt_cls, (-1,)),
        ignore_label=-1, use_ignore=True, normalization="valid",
        name="cls_prob")
    loc_loss = sym.MakeLoss(
        sym.smooth_l1((loc_pred - tgt_loc) * tgt_mask, scalar=1.0),
        name="loc_loss")
    return sym.Group([cls_loss, loc_loss, sym.BlockGrad(anchor)])
