#!/usr/bin/env python
"""Train an LSTM language model with bucketing.

Reference: ``example/rnn/bucketing/lstm_bucketing.py`` — variable-length
sentences grouped into buckets, one executor per bucket sharing weights
(BucketingModule), perplexity metric.

With no corpus on disk a synthetic language is generated: a first-order
Markov chain with a strongly-peaked transition table, so an LSTM that
learns bigram statistics drives perplexity far below the uniform
baseline.  Runs fully offline:

    python examples/train_lm.py --num-epochs 5
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))


def synthetic_corpus(vocab=50, num_sentences=800, seed=11):
    """Markov-chain sentences with random lengths (deterministic).
    Token id 0 is reserved for padding — real tokens are 1..vocab-1."""
    rng = np.random.RandomState(seed)
    # peaked transitions: each token has ~3 likely successors
    real = vocab - 1
    trans = np.full((real, real), 1e-3)
    for v in range(real):
        nxt = rng.choice(real, 3, replace=False)
        trans[v, nxt] = 1.0
    trans /= trans.sum(1, keepdims=True)
    sentences = []
    for _ in range(num_sentences):
        length = rng.choice([8, 12, 16, 20])
        s = [int(rng.randint(real))]
        for _ in range(length - 1):
            s.append(int(rng.choice(real, p=trans[s[-1]])))
        sentences.append([t + 1 for t in s])  # shift: 0 stays padding
    return sentences


def main():
    parser = argparse.ArgumentParser(description="bucketing LSTM LM")
    parser.add_argument("--vocab", type=int, default=50)
    parser.add_argument("--num-hidden", type=int, default=64)
    parser.add_argument("--num-embed", type=int, default=32)
    parser.add_argument("--num-layers", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--num-sentences", type=int, default=800)
    parser.add_argument("--max-perplexity", type=float, default=None,
                        help="exit nonzero unless final train perplexity "
                             "is below this")
    args = parser.parse_args()

    import mxnet_tpu as mx

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    sentences = synthetic_corpus(args.vocab, args.num_sentences)
    buckets = [8, 12, 16, 20]
    invalid_label = 0
    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets,
                                      invalid_label=invalid_label)

    # one parameter set shared by every bucket's executor
    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=args.vocab,
                                 output_dim=args.num_embed, name="embed")
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=args.vocab,
                                     name="pred")
        label = mx.sym.reshape(label, shape=(-1,))
        # padding positions (label 0) contribute no loss
        pred = mx.sym.SoftmaxOutput(data=pred, label=label,
                                    use_ignore=True,
                                    ignore_label=invalid_label,
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=train.default_bucket_key)

    perplexity = mx.metric.Perplexity(ignore_label=invalid_label)
    model.fit(train,
              eval_metric=perplexity,
              optimizer="adam",
              optimizer_params={"learning_rate": args.lr},
              initializer=mx.init.Xavier(),
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(
                  args.batch_size, 20))

    # final perplexity over the training data
    train.reset()
    perplexity.reset()
    for batch in train:
        model.forward(batch, is_train=False)
        model.update_metric(perplexity, batch.label)
    final = perplexity.get()[1]
    print("final train perplexity: %.3f (uniform baseline %.1f)"
          % (final, args.vocab))
    if args.max_perplexity is not None and final > args.max_perplexity:
        print("FAILED: perplexity %.3f > %.3f" % (final,
                                                  args.max_perplexity))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
