"""DCGAN on synthetic data (reference: example/gan/dcgan.py).

Generator: Deconvolution stack (4x4 -> 16x16); discriminator: strided
conv stack.  The 'real' distribution is structured noise (smooth
low-frequency blobs), so the discriminator has an actual signal to
learn and the adversarial dynamics are testable offline:

    JAX_PLATFORMS=cpu python examples/train_dcgan.py

Both nets hybridize to single XLA programs; the alternating update is
the standard two-Trainer gluon loop.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_generator(mx, ngf=16, nz=16):
    nn = mx.gluon.nn
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # z (B, nz, 1, 1) -> (B, ngf*2, 4, 4)
        net.add(nn.Conv2DTranspose(ngf * 2, 4, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # 4 -> 8
                nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # 8 -> 16
                nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                   use_bias=False),
                nn.Activation("tanh"))
    return net


def build_discriminator(mx, ndf=16):
    nn = mx.gluon.nn
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1),       # 16->8
                nn.LeakyReLU(0.2),
                nn.Conv2D(ndf * 2, 4, strides=2, padding=1),   # 8->4
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(1, 4))                               # 4->1
    return net


def real_batch(rng, n, size=16):
    """Smooth blobs: random low-res noise upsampled — learnably
    different from the generator's initial output."""
    lo = rng.randn(n, 1, 4, 4).astype(np.float32)
    img = lo.repeat(size // 4, axis=2).repeat(size // 4, axis=3)
    return np.tanh(img)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-steps", type=int, default=120)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--nz", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-4)
    args = parser.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd, nd

    gen = build_generator(mx, nz=args.nz)
    disc = build_discriminator(mx)
    for net in (gen, disc):
        net.initialize(mx.init.Normal(0.02))
        net.hybridize()

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    t_gen = gluon.Trainer(gen.collect_params(), "adam",
                          {"learning_rate": args.lr, "beta1": 0.5})
    t_disc = gluon.Trainer(disc.collect_params(), "adam",
                           {"learning_rate": args.lr, "beta1": 0.5})

    rng = np.random.RandomState(0)
    bs = args.batch_size
    ones = nd.array(np.ones((bs,), np.float32))
    zeros = nd.array(np.zeros((bs,), np.float32))
    d_losses, g_losses = [], []
    for step in range(args.num_steps):
        real = nd.array(real_batch(rng, bs))
        z = nd.array(rng.randn(bs, args.nz, 1, 1).astype(np.float32))
        # --- discriminator: real -> 1, fake -> 0 -----------------------
        with autograd.record():
            out_r = disc(real).reshape((bs,))
            fake = gen(z)
            out_f = disc(fake.detach()).reshape((bs,))
            d_loss = loss_fn(out_r, ones) + loss_fn(out_f, zeros)
        d_loss.backward()
        t_disc.step(bs)
        # --- generator: fool the discriminator -------------------------
        with autograd.record():
            out_f = disc(gen(z)).reshape((bs,))
            g_loss = loss_fn(out_f, ones)
        g_loss.backward()
        t_gen.step(bs)
        d_losses.append(float(nd.mean(d_loss).asnumpy()))
        g_losses.append(float(nd.mean(g_loss).asnumpy()))
        if step % 30 == 0:
            print("step %3d  d_loss %.4f  g_loss %.4f"
                  % (step, d_losses[-1], g_losses[-1]), flush=True)

    # adversarial sanity: D learned something early on (loss fell from
    # its random-init level) and the game didn't blow up
    early = np.mean(d_losses[:10])
    late = np.mean(d_losses[-20:])
    img = gen(nd.array(rng.randn(4, args.nz, 1, 1)
                       .astype(np.float32))).asnumpy()
    assert img.shape == (4, 1, 16, 16)
    assert np.isfinite(img).all()
    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    if args.num_steps >= 40:   # windows disjoint: the trend is real
        assert late < early, (early, late)
    print("DCGAN-OK d %.4f -> %.4f" % (early, late), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
