#!/usr/bin/env python
"""Train LeNet / MLP on MNIST.

Reference: ``example/image-classification/train_mnist.py`` (symbol
definitions + MNISTIter data path through common/fit.py).

With no MNIST files on disk this falls back to a deterministic synthetic
digit set (class-dependent blob patterns + noise) so the script — and the
distributed convergence test that drives it — runs fully offline.

Single process:   python examples/train_mnist.py --network lenet
Distributed:      python tools/launch.py -n 2 python \
                      examples/train_mnist.py --kv-store dist_sync
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))  # repo root (mxnet_tpu pkg)
import common  # noqa: E402


def mlp(num_classes=10):
    import mxnet_tpu as mx
    data = mx.sym.var("data")
    data = mx.sym.Flatten(data=data)
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    act1 = mx.sym.Activation(data=fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(data=act1, num_hidden=64, name="fc2")
    act2 = mx.sym.Activation(data=fc2, act_type="relu", name="relu2")
    fc3 = mx.sym.FullyConnected(data=act2, num_hidden=num_classes,
                                name="fc3")
    return mx.sym.SoftmaxOutput(data=fc3, name="softmax")


def lenet(num_classes=10):
    import mxnet_tpu as mx
    data = mx.sym.var("data")
    conv1 = mx.sym.Convolution(data=data, kernel=(5, 5), num_filter=20,
                               name="conv1")
    tanh1 = mx.sym.Activation(data=conv1, act_type="tanh")
    pool1 = mx.sym.Pooling(data=tanh1, pool_type="max", kernel=(2, 2),
                           stride=(2, 2))
    conv2 = mx.sym.Convolution(data=pool1, kernel=(5, 5), num_filter=50,
                               name="conv2")
    tanh2 = mx.sym.Activation(data=conv2, act_type="tanh")
    pool2 = mx.sym.Pooling(data=tanh2, pool_type="max", kernel=(2, 2),
                           stride=(2, 2))
    flat = mx.sym.Flatten(data=pool2)
    fc1 = mx.sym.FullyConnected(data=flat, num_hidden=500, name="fc1")
    tanh3 = mx.sym.Activation(data=fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(data=tanh3, num_hidden=num_classes,
                                name="fc2")
    return mx.sym.SoftmaxOutput(data=fc2, name="softmax")


def synthetic_mnist(num_examples, seed=42):
    """Learnable synthetic digits: each class lights a distinct 7x7 cell
    grid region, plus noise.  Deterministic across workers."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=num_examples).astype(np.float32)
    images = rng.uniform(0, 0.3, size=(num_examples, 1, 28, 28)) \
        .astype(np.float32)
    for i, lab in enumerate(labels.astype(int)):
        r, c = divmod(lab, 4)
        images[i, 0, 2 + r * 9:9 + r * 9, 2 + c * 6:8 + c * 6] += 0.7
    return images, labels


def get_iters(args, kv):
    import mxnet_tpu as mx
    data_dir = getattr(args, "data_dir", "data")
    mnist_files = [os.path.join(data_dir, f) for f in
                   ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
                    "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")]
    rank = kv.rank if kv is not None else 0
    nworker = kv.num_workers if kv is not None else 1
    if all(os.path.exists(f) for f in mnist_files):
        train = mx.io.MNISTIter(
            image=mnist_files[0], label=mnist_files[1],
            batch_size=args.batch_size, shuffle=True,
            num_parts=nworker, part_index=rank)
        val = mx.io.MNISTIter(
            image=mnist_files[2], label=mnist_files[3],
            batch_size=args.batch_size, shuffle=False)
        return train, val
    # offline fallback: synthetic digits, sharded by worker rank
    x, y = synthetic_mnist(args.num_examples)
    xv, yv = synthetic_mnist(max(args.batch_size * 4, 512), seed=1234)
    x, y = x[rank::nworker], y[rank::nworker]
    train = mx.io.NDArrayIter(data=x, label=y,
                              batch_size=args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(data=xv, label=yv,
                            batch_size=args.batch_size,
                            label_name="softmax_label")
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.set_defaults(network="mlp", num_epochs=3, batch_size=64,
                        lr=0.05, disp_batches=50)
    common.add_fit_args(parser)
    parser.add_argument("--data-dir", type=str, default="data",
                        help="directory with the idx-ubyte MNIST files")
    parser.add_argument("--num-examples", type=int, default=4096,
                        help="synthetic-fallback training-set size")
    parser.add_argument("--min-accuracy", type=float, default=None,
                        help="exit nonzero unless final train accuracy "
                             "reaches this (used by the dist tests)")
    args = parser.parse_args()

    net = lenet() if args.network == "lenet" else mlp()
    mod = common.fit(args, net, get_iters)

    if args.min_accuracy is not None:
        import mxnet_tpu as mx
        train, _ = get_iters(args, None)
        acc = mod.score(train, mx.metric.create("accuracy"))
        acc_val = dict(acc)["accuracy"]
        print("final train accuracy: %.4f" % acc_val)
        if acc_val < args.min_accuracy:
            print("FAILED: accuracy %.4f < required %.4f"
                  % (acc_val, args.min_accuracy))
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
