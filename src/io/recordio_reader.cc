// Native RecordIO reader — the C++ half of the data pipeline
// (reference: src/io/iter_image_recordio_2.cc chunk reading +
// src/io/image_recordio.h framing; dmlc-core recordio streams).
//
// Framing (identical to mxnet_tpu/recordio.py and the reference):
//   [magic u32][lrecord u32][data][pad to 4B]
//   lrecord = cflag(3 bits) << 29 | length(29 bits)
// Multi-part records (cflag 1=begin, 2=middle, 3=end) are reassembled.
//
// Pure C ABI, no Python dependency: the Python side drives it via
// ctypes (mxnet_tpu/recordio_native.py) and keeps the cv2 decode pool;
// this layer does file IO, framing, and index lookup natively — the
// part the reference implements in C++ too.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#define RIO_API extern "C" __attribute__((visibility("default")))

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
  FILE* f = nullptr;
  long file_size = 0;
  std::vector<uint8_t> buf;     // last record (reassembled)
  std::string err;
};

thread_local std::string g_err;

bool read_u32(FILE* f, uint32_t* out) {
  return std::fread(out, sizeof(uint32_t), 1, f) == 1;
}

// Read one framed record part; returns: 1 ok, 0 eof, -1 error.
int read_part(Reader* r, uint32_t* cflag, std::vector<uint8_t>* data) {
  FILE* f = r->f;
  uint32_t magic;
  if (!read_u32(f, &magic)) return 0;  // clean EOF
  if (magic != kMagic) {
    g_err = "bad magic — corrupt or not a RecordIO file";
    return -1;
  }
  uint32_t lrec;
  if (!read_u32(f, &lrec)) {
    g_err = "truncated record header";
    return -1;
  }
  *cflag = lrec >> 29;
  uint32_t len = lrec & ((1u << 29) - 1);
  // validate against remaining bytes BEFORE allocating: a corrupt
  // length field must not trigger a ~512MB resize (bad_alloc crossing
  // the C ABI would be UB)
  long pos = std::ftell(f);
  if (pos < 0 || static_cast<long>(len) > r->file_size - pos) {
    g_err = "record length exceeds file size — corrupt file";
    return -1;
  }
  size_t off = data->size();
  data->resize(off + len);
  if (len && std::fread(data->data() + off, 1, len, f) != len) {
    g_err = "truncated record payload";
    return -1;
  }
  uint32_t pad = (4 - (len & 3)) & 3;
  if (pad) std::fseek(f, pad, SEEK_CUR);
  return 1;
}

}  // namespace

RIO_API const char* RIOGetLastError() { return g_err.c_str(); }

RIO_API void* RIOOpen(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    g_err = std::string("cannot open ") + path;
    return nullptr;
  }
  Reader* r = new Reader();
  r->f = f;
  std::fseek(f, 0, SEEK_END);
  r->file_size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  return r;
}

RIO_API void RIOClose(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r->f) std::fclose(r->f);
  delete r;
}

RIO_API void RIOReset(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  std::fseek(r->f, 0, SEEK_SET);
}

RIO_API int RIOSeek(void* handle, long offset) {
  Reader* r = static_cast<Reader*>(handle);
  return std::fseek(r->f, offset, SEEK_SET) == 0 ? 0 : -1;
}

RIO_API long RIOTell(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  return std::ftell(r->f);
}

// Read the next logical record (reassembling multi-part records).
// Returns 1 with *data/*size set (valid until the next call), 0 at EOF,
// -1 on error.
RIO_API int RIONext(void* handle, const uint8_t** data, uint64_t* size) {
  Reader* r = static_cast<Reader*>(handle);
  r->buf.clear();
  uint32_t cflag = 0;
  int rc = read_part(r, &cflag, &r->buf);
  if (rc <= 0) return rc;
  if (cflag == 1) {  // multi-part: keep reading until the end part
    while (true) {
      rc = read_part(r, &cflag, &r->buf);
      if (rc < 0) return -1;  // keep read_part's specific error
      if (rc == 0) {
        g_err = "EOF inside a multi-part record";
        return -1;
      }
      if (cflag == 3) break;
      if (cflag != 2) {
        g_err = "unexpected cflag inside multi-part record";
        return -1;
      }
    }
  }
  *data = r->buf.data();
  *size = r->buf.size();
  return 1;
}

// Scan forward FROM THE CURRENT POSITION, appending record start
// offsets (for building the .idx the reference's im2rec produces).
// Returns the count written (< max_n means EOF reached), or -1 on
// error.  Callers reset first (RIOReset) and may call repeatedly with a
// bounded buffer to index arbitrarily large files.
RIO_API long RIOBuildIndex(void* handle, uint64_t* offsets, long max_n) {
  Reader* r = static_cast<Reader*>(handle);
  long n = 0;
  while (n < max_n) {
    long pos = std::ftell(r->f);
    const uint8_t* d;
    uint64_t sz;
    int rc = RIONext(r, &d, &sz);
    if (rc == 0) break;
    if (rc < 0) return -1;
    offsets[n++] = static_cast<uint64_t>(pos);
  }
  return n;
}
