// libjpeg decode + augment worker team for ImageRecordIter.
//
// Reference design: src/io/iter_image_recordio_2.cc:141-149 — an OMP
// team decodes JPEG records and augments them straight into the batch
// buffer.  This is the same shape as a persistent pthread pool: one
// MXIOPoolDecodeBatch call fans a batch of encoded buffers across the
// team; each worker decodes (with libjpeg's fractional DCT scaling to
// skip resolution the pipeline will discard), resizes the shorter side
// (bilinear), crops (center or seeded-random), optionally mirrors, and
// writes RGB uint8 rows directly into its slot of the caller's batch
// buffer — no per-image Python object, no GIL, throughput scales with
// cores.
//
// Build: make -C src/io  (links -ljpeg; ctypes consumer:
// mxnet_tpu/io/native_decode.py)

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

struct DecodeCfg {
  int32_t resize;       // shorter-side target before crop; 0 = off
  int32_t out_h;
  int32_t out_w;
  int32_t rand_crop;    // else center crop
  int32_t rand_mirror;  // else never
};

// libjpeg error handling: longjmp out instead of exit()
struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* j = reinterpret_cast<JerrMgr*>(cinfo->err);
  longjmp(j->jb, 1);
}

// xorshift64 — per-image deterministic augment RNG (seed from caller)
inline uint64_t next_rand(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x;
}

// bilinear resize RGB u8 (src_h, src_w) -> (dst_h, dst_w); column
// coefficients are precomputed once, the inner loop is fixed-point
void resize_bilinear(const uint8_t* src, int sh, int sw, uint8_t* dst,
                     int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, size_t(sh) * sw * 3);
    return;
  }
  const float ry = dh > 1 ? float(sh - 1) / float(dh - 1) : 0.f;
  const float rx = dw > 1 ? float(sw - 1) / float(dw - 1) : 0.f;
  std::vector<int> x0s(dw), x1s(dw), wxs(dw);  // wx in 1/256ths
  for (int x = 0; x < dw; ++x) {
    float fx = x * rx;
    int x0 = int(fx);
    x0s[x] = x0 * 3;
    x1s[x] = (x0 + 1 < sw ? x0 + 1 : x0) * 3;
    wxs[x] = int((fx - x0) * 256.0f + 0.5f);
  }
  for (int y = 0; y < dh; ++y) {
    float fy = y * ry;
    int y0 = int(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    int wy = int((fy - y0) * 256.0f + 0.5f);
    const uint8_t* r0 = src + size_t(y0) * sw * 3;
    const uint8_t* r1 = src + size_t(y1) * sw * 3;
    uint8_t* drow = dst + size_t(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const int x0 = x0s[x], x1 = x1s[x], wx = wxs[x];
      for (int c = 0; c < 3; ++c) {
        int top = r0[x0 + c] * (256 - wx) + r0[x1 + c] * wx;
        int bot = r1[x0 + c] * (256 - wx) + r1[x1 + c] * wx;
        drow[x * 3 + c] =
            uint8_t((top * (256 - wy) + bot * wy + 32768) >> 16);
      }
    }
  }
}

// decode+augment ONE image into out (out_h*out_w*3); returns 0 on ok
int decode_one(const uint8_t* buf, size_t len, const DecodeCfg& cfg,
               uint64_t seed, uint8_t* out,
               std::vector<uint8_t>* scratch_a,
               std::vector<uint8_t>* scratch_b) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  cinfo.out_color_space = JCS_RGB;
  // fractional decode: keep the smallest scale whose shorter side
  // still covers what the pipeline needs (resize target or crop)
  const int need = cfg.resize > 0
                       ? cfg.resize
                       : (cfg.out_h > cfg.out_w ? cfg.out_h : cfg.out_w);
  const int short_side = cinfo.image_height < cinfo.image_width
                             ? cinfo.image_height
                             : cinfo.image_width;
  int denom = 1;
  while (denom < 8 && short_side / (denom * 2) >= need) denom *= 2;
  cinfo.scale_num = 1;
  cinfo.scale_denom = denom;
  // plain chroma upsampling (fancy costs ~10% for training-invisible
  // quality; JDCT_IFAST measured SLOWER than the default on the
  // scaled-decode path here, so the IDCT stays default)
  cinfo.do_fancy_upsampling = FALSE;
  jpeg_start_decompress(&cinfo);
  const int sw = cinfo.output_width, sh = cinfo.output_height;
  scratch_a->resize(size_t(sw) * sh * 3);
  uint8_t* rows = scratch_a->data();
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = rows + size_t(cinfo.output_scanline) * sw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // grayscale/CMYK already converted to RGB by libjpeg (JCS_RGB)
  const uint8_t* cur = rows;
  int ch = sh, cw = sw;
  if (cfg.resize > 0 && short_side != 0) {
    // shorter side -> cfg.resize, aspect preserved
    int dh, dw;
    if (sh <= sw) {
      dh = cfg.resize;
      dw = int(int64_t(sw) * cfg.resize / sh);
    } else {
      dw = cfg.resize;
      dh = int(int64_t(sh) * cfg.resize / sw);
    }
    scratch_b->resize(size_t(dh) * dw * 3);
    resize_bilinear(cur, ch, cw, scratch_b->data(), dh, dw);
    cur = scratch_b->data();
    ch = dh;
    cw = dw;
  }
  if (ch < cfg.out_h || cw < cfg.out_w) {
    // too small even after resize: upscale to the crop size
    std::vector<uint8_t>* dst = (cur == scratch_b->data())
                                    ? scratch_a
                                    : scratch_b;
    dst->resize(size_t(cfg.out_h) * cfg.out_w * 3);
    resize_bilinear(cur, ch, cw, dst->data(), cfg.out_h, cfg.out_w);
    cur = dst->data();
    ch = cfg.out_h;
    cw = cfg.out_w;
  }
  uint64_t rng = seed ? seed : 0x9e3779b97f4a7c15ull;
  int cy = (ch - cfg.out_h) / 2, cx = (cw - cfg.out_w) / 2;
  if (cfg.rand_crop) {
    cy = int(next_rand(&rng) % uint64_t(ch - cfg.out_h + 1));
    cx = int(next_rand(&rng) % uint64_t(cw - cfg.out_w + 1));
  }
  const bool mirror = cfg.rand_mirror && (next_rand(&rng) & 1);
  for (int y = 0; y < cfg.out_h; ++y) {
    const uint8_t* srow = cur + (size_t(cy + y) * cw + cx) * 3;
    uint8_t* drow = out + size_t(y) * cfg.out_w * 3;
    if (!mirror) {
      std::memcpy(drow, srow, size_t(cfg.out_w) * 3);
    } else {
      for (int x = 0; x < cfg.out_w; ++x) {
        const uint8_t* s = srow + size_t(cfg.out_w - 1 - x) * 3;
        drow[x * 3 + 0] = s[0];
        drow[x * 3 + 1] = s[1];
        drow[x * 3 + 2] = s[2];
      }
    }
  }
  return 0;
}

struct Pool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  bool stop = false;
  // current batch job (written by RunBatch under mu)
  const uint8_t* const* bufs = nullptr;
  const size_t* lens = nullptr;
  int n = 0;
  const DecodeCfg* cfg = nullptr;
  const uint64_t* seeds = nullptr;
  uint8_t* out = nullptr;
  int32_t* rcs = nullptr;
  std::atomic<int> next_idx{0};
  int entered = 0;   // workers that joined this job; guarded by mu
  int in_loop = 0;   // workers inside the claim loop; guarded by mu
  uint64_t job_id = 0;

  explicit Pool(int n_threads) {
    for (int t = 0; t < n_threads; ++t)
      workers.emplace_back([this] { Work(); });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& w : workers) w.join();
  }

  // Barrier semantics: EVERY worker checks into every job under mu
  // before claiming, and RunBatch returns only when all of them have
  // entered AND left the claim loop — so no straggler can ever touch a
  // later job's counters or read half-rewritten job state, and every
  // claimed index is fully decoded at return.  All condvar transitions
  // happen with mu held — no lost wakeups.
  void Work() {
    std::vector<uint8_t> sa, sb;  // per-thread scratch, reused
    uint64_t seen_job = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stop || job_id != seen_job; });
        if (stop) return;
        seen_job = job_id;
        ++entered;
        ++in_loop;
      }
      const size_t out_sz = size_t(cfg->out_h) * cfg->out_w * 3;
      for (;;) {
        int i = next_idx.fetch_add(1);
        if (i >= n) break;
        rcs[i] = decode_one(bufs[i], lens[i], *cfg, seeds[i],
                            out + out_sz * i, &sa, &sb);
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        if (--in_loop == 0 &&
            entered == static_cast<int>(workers.size()))
          cv_done.notify_all();
      }
    }
  }

  void RunBatch(const uint8_t* const* b, const size_t* l, int count,
                const DecodeCfg* c, const uint64_t* s, uint8_t* o,
                int32_t* r) {
    std::unique_lock<std::mutex> lk(mu);
    bufs = b;
    lens = l;
    n = count;
    cfg = c;
    seeds = s;
    out = o;
    rcs = r;
    next_idx.store(0);
    entered = 0;
    ++job_id;
    cv_work.notify_all();
    cv_done.wait(lk, [&] {
      return entered == static_cast<int>(workers.size()) &&
             in_loop == 0;
    });
  }
};

}  // namespace

MXTPU_API void* MXIOPoolCreate(int n_threads) {
  if (n_threads < 1) n_threads = 1;
  return new Pool(n_threads);
}

MXTPU_API void MXIOPoolFree(void* pool) {
  delete static_cast<Pool*>(pool);
}

// out: n * out_h * out_w * 3 uint8 RGB (HWC per image); rcs[i] != 0
// marks image i undecodable (its slot is left as-is).
MXTPU_API int MXIOPoolDecodeBatch(void* pool, const uint8_t* const* bufs,
                                  const size_t* lens, int n,
                                  const DecodeCfg* cfg,
                                  const uint64_t* seeds, uint8_t* out,
                                  int32_t* rcs) {
  // every pointer is caller-provided over the C ABI: reject nulls
  // instead of crashing the process (cfg was dereferenced unchecked)
  if (!pool || !bufs || !lens || !cfg || !seeds || !out || !rcs)
    return -1;
  if (n <= 0 || cfg->out_h <= 0 || cfg->out_w <= 0) return -1;
  static_cast<Pool*>(pool)->RunBatch(bufs, lens, n, cfg, seeds, out,
                                     rcs);
  return 0;
}
