// Shared embedded-CPython bootstrap for the C ABI libraries
// (mxtpu_predict.cc, mxtpu_ndarray.cc).  Header-only: each .so is built
// standalone, so the helpers live in an anonymous namespace per TU.
#ifndef MXTPU_EMBED_PYTHON_H_
#define MXTPU_EMBED_PYTHON_H_

#include <Python.h>

#include <dlfcn.h>

#include <mutex>
#include <string>

namespace {

thread_local std::string g_last_error;

// Bring up the interpreter once (for hosts that never initialized
// Python themselves); must run before any PyGILState_Ensure.
inline void EnsureInterpreter() {
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      // When THIS library was dlopen'd (perl/ruby/FFI hosts), libpython
      // came in RTLD_LOCAL and Python's own extension modules (math,
      // _struct, ...) then fail with unresolved Py* symbols.  Promote
      // the already-mapped libpython to global scope first; harmless
      // when the host linked libpython itself (C example, ctypes).
#ifdef MXTPU_PYLIB_SONAME
      if (!dlopen(MXTPU_PYLIB_SONAME,
                  RTLD_GLOBAL | RTLD_NOLOAD | RTLD_LAZY)) {
        dlopen(MXTPU_PYLIB_SONAME, RTLD_GLOBAL | RTLD_LAZY);
      }
#endif
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      // release the GIL taken by Py_Initialize so GILGuard can take it
      PyEval_SaveThread();
    }
  });
}

class GILGuard {
 public:
  GILGuard() {
    EnsureInterpreter();
    state_ = PyGILState_Ensure();
  }
  ~GILGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Capture the pending Python exception into g_last_error.
inline void SetErrorFromPython() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

}  // namespace

#endif  // MXTPU_EMBED_PYTHON_H_
