// C predict ABI for the TPU-native framework.
//
// Mirrors the reference's standalone inference surface
// (include/mxnet/c_predict_api.h:78-207: MXPredCreate / MXPredSetInput /
// MXPredForward / MXPredGetOutputShape / MXPredGetOutput / MXPredFree,
// MXNDListCreate / MXNDListGet / MXNDListFree, MXGetLastError).
//
// Architecture: the reference links the whole engine+executor into
// libmxnet.so and walks it from C (src/c_api/c_predict_api.cc).  Here the
// compute path is XLA, reached through the Python runtime, so this library
// embeds CPython and forwards each ABI call to
// mxnet_tpu/capi_bridge.py; only raw float buffers, shapes, and error
// strings cross the C boundary.  Consumers need no Python headers —
// the ABI below is plain C, loadable via dlopen/ctypes/FFI from any
// language, which is what the reference's L10 bindings (SURVEY §2.6)
// actually require of L8.
//
// Thread-safety: every entry point acquires the GIL (the embedded
// interpreter may be shared with a host application's Python).

#include <Python.h>

#include "embed_python.h"

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* PredictorHandle;
typedef void* NDListHandle;
typedef uint32_t mx_uint;
typedef float mx_float;

namespace {


struct Predictor {
  PyObject* obj;  // capi_bridge.Predictor
  // cached output buffer + shape so pointers stay valid until next call
  std::string out_bytes;
  std::vector<mx_uint> out_shape;
};

struct NDList {
  PyObject* list;  // [(name, shape_tuple, bytes)]
  std::string cur_name;
  std::vector<mx_uint> cur_shape;
  std::string cur_bytes;
};

// Import the bridge module (caller holds the GIL via GILGuard).
PyObject* GetBridge() {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.capi_bridge");
  return mod;  // may be nullptr with python error set
}

}  // namespace

MXTPU_API const char* MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXPredCreate(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes,
                           const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           PredictorHandle* out) {
  GILGuard gil;
  PyObject* bridge = GetBridge();
  if (!bridge) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* keys = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyTuple_SetItem(shp, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* pred = PyObject_CallMethod(
      bridge, "create", "sOiiOO", symbol_json_str, params, dev_type,
      dev_id, keys, shapes);
  Py_DECREF(params);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  Py_DECREF(bridge);
  if (!pred) {
    SetErrorFromPython();
    return -1;
  }
  Predictor* h = new Predictor();
  h->obj = pred;
  *out = h;
  return 0;
}

MXTPU_API int MXPredSetInput(PredictorHandle handle, const char* key,
                             const mx_float* data, mx_uint size) {
  GILGuard gil;
  Predictor* h = static_cast<Predictor*>(handle);
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(mx_float));
  // shape: flat (the bridge reshapes to the bound input's shape)
  PyObject* r = PyObject_CallMethod(h->obj, "set_input_flat", "sO", key,
                                    bytes);
  Py_DECREF(bytes);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredForward(PredictorHandle handle) {
  GILGuard gil;
  Predictor* h = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(h->obj, "forward", nullptr);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                                   mx_uint** shape_data,
                                   mx_uint* shape_ndim) {
  GILGuard gil;
  Predictor* h = static_cast<Predictor*>(handle);
  PyObject* shp = PyObject_CallMethod(h->obj, "get_output_shape", "I",
                                      index);
  if (!shp) {
    SetErrorFromPython();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(shp);
  h->out_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->out_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i)));
  Py_DECREF(shp);
  *shape_data = h->out_shape.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

MXTPU_API int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                              mx_float* data, mx_uint size) {
  GILGuard gil;
  Predictor* h = static_cast<Predictor*>(handle);
  PyObject* bytes = PyObject_CallMethod(h->obj, "get_output", "I", index);
  if (!bytes) {
    SetErrorFromPython();
    return -1;
  }
  char* buf;
  Py_ssize_t len;
  if (PyBytes_AsStringAndSize(bytes, &buf, &len) != 0) {
    Py_DECREF(bytes);
    SetErrorFromPython();
    return -1;
  }
  if (static_cast<size_t>(len) != size * sizeof(mx_float)) {
    Py_DECREF(bytes);
    g_last_error = "MXPredGetOutput: size mismatch";
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(bytes);
  return 0;
}

MXTPU_API int MXPredFree(PredictorHandle handle) {
  GILGuard gil;
  Predictor* h = static_cast<Predictor*>(handle);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

MXTPU_API int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                             NDListHandle* out, mx_uint* out_length) {
  GILGuard gil;
  PyObject* bridge = GetBridge();
  if (!bridge) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* raw = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject* list = PyObject_CallMethod(bridge, "ndlist_load", "O", raw);
  Py_DECREF(raw);
  Py_DECREF(bridge);
  if (!list) {
    SetErrorFromPython();
    return -1;
  }
  NDList* h = new NDList();
  h->list = list;
  *out = h;
  *out_length = static_cast<mx_uint>(PyList_Size(list));
  return 0;
}

MXTPU_API int MXNDListGet(NDListHandle handle, mx_uint index,
                          const char** out_key, const mx_float** out_data,
                          const mx_uint** out_shape, mx_uint* out_ndim) {
  GILGuard gil;
  NDList* h = static_cast<NDList*>(handle);
  PyObject* item = PyList_GetItem(h->list, index);  // borrowed
  if (!item) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* name = PyTuple_GetItem(item, 0);
  PyObject* shape = PyTuple_GetItem(item, 1);
  PyObject* bytes = PyTuple_GetItem(item, 2);
  h->cur_name = PyUnicode_AsUTF8(name);
  Py_ssize_t n = PyTuple_Size(shape);
  h->cur_shape.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->cur_shape[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shape, i)));
  char* buf;
  Py_ssize_t len;
  PyBytes_AsStringAndSize(bytes, &buf, &len);
  h->cur_bytes.assign(buf, len);
  *out_key = h->cur_name.c_str();
  *out_data = reinterpret_cast<const mx_float*>(h->cur_bytes.data());
  *out_shape = h->cur_shape.data();
  *out_ndim = static_cast<mx_uint>(n);
  return 0;
}

MXTPU_API int MXNDListFree(NDListHandle handle) {
  GILGuard gil;
  NDList* h = static_cast<NDList*>(handle);
  Py_XDECREF(h->list);
  delete h;
  return 0;
}
