// NDArray + operator-invoke C ABI (see include/mxtpu/c_api.h).
//
// Same architecture as mxtpu_predict.cc: the compute path is XLA via the
// Python runtime, so this library embeds CPython and forwards each call
// to mxnet_tpu/capi_bridge.py.  An NDArrayHandle is an owned PyObject*
// of a framework NDArray; everything else crossing the boundary is raw
// bytes, ints and strings.  Every entry point takes the GIL.

#include <Python.h>

#include "embed_python.h"

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* NDArrayHandle;
typedef uint32_t mx_uint;

namespace {

// results that must outlive the call that produced them
thread_local std::vector<mx_uint> g_shape;
thread_local std::vector<NDArrayHandle> g_outputs;
thread_local std::string g_op_names;
thread_local std::vector<NDArrayHandle> g_loaded;
thread_local std::vector<std::string> g_loaded_name_store;
thread_local std::vector<const char*> g_loaded_names;

PyObject* GetBridge() {
  return PyImport_ImportModule("mxnet_tpu.capi_bridge");
}

// Call bridge.<method>(...) with a pre-built args tuple (steals nothing).
PyObject* CallBridge(const char* method, PyObject* args) {
  PyObject* bridge = GetBridge();
  if (!bridge) return nullptr;
  PyObject* fn = PyObject_GetAttrString(bridge, method);
  Py_DECREF(bridge);
  if (!fn) return nullptr;
  PyObject* r = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return r;
}

}  // namespace

MXTPU_API const char* MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int* out) {
  *out = 10301;  // reference parity line (1.3.1)
  return 0;
}

MXTPU_API int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              int dtype, NDArrayHandle* out) {
  (void)dev_id;
  (void)delay_alloc;
  GILGuard gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oii)", shp, dtype, dev_type);
  Py_DECREF(shp);
  PyObject* nd = CallBridge("nd_create", args);
  Py_DECREF(args);
  if (!nd) {
    SetErrorFromPython();
    return -1;
  }
  *out = nd;  // ownership transfers to the handle
  return 0;
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void* data,
                                       size_t size_bytes) {
  GILGuard gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), size_bytes);
  PyObject* args = Py_BuildValue("(OO)",
                                 static_cast<PyObject*>(handle), bytes);
  Py_DECREF(bytes);
  PyObject* r = CallBridge("nd_copy_from_bytes", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                                     size_t size_bytes) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_to_bytes", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    SetErrorFromPython();
    return -1;
  }
  if (static_cast<size_t>(n) > size_bytes) {
    Py_DECREF(r);
    g_last_error = "destination buffer too small";
    return -1;
  }
  std::memcpy(data, buf, n);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                                const mx_uint** out_pdata) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_shape", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(r); ++i)
    g_shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(r, i))));
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(g_shape.size());
  *out_pdata = g_shape.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_dtype", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out_dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXImperativeInvoke(const char* op_name, int num_inputs,
                                 NDArrayHandle* inputs, int* num_outputs,
                                 NDArrayHandle** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals) {
  GILGuard gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject* params = PyDict_New();
  for (int i = 0; i < num_params; ++i) {
    PyObject* v = PyUnicode_FromString(param_vals[i]);
    PyDict_SetItemString(params, param_keys[i], v);  // does not steal
    Py_DECREF(v);
  }
  PyObject* args = Py_BuildValue("(sOO)", op_name, ins, params);
  Py_DECREF(ins);
  Py_DECREF(params);
  PyObject* r = CallBridge("nd_invoke", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_outputs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject* o = PyList_GetItem(r, i);
    Py_INCREF(o);  // each output handle is caller-owned
    g_outputs.push_back(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(g_outputs.size());
  *outputs = g_outputs.data();
  return 0;
}

MXTPU_API int MXListAllOpNames(const char** out_names) {
  GILGuard gil;
  PyObject* args = PyTuple_New(0);
  PyObject* r = CallBridge("nd_list_ops", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  const char* c = PyUnicode_AsUTF8(r);
  g_op_names = c ? c : "";
  Py_DECREF(r);
  *out_names = g_op_names.c_str();
  return 0;
}

MXTPU_API int MXNDArraySave(const char* fname, mx_uint num_args,
                            NDArrayHandle* args_in, const char** keys) {
  GILGuard gil;
  PyObject* arrs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* o = static_cast<PyObject*>(args_in[i]);
    Py_INCREF(o);
    PyList_SetItem(arrs, i, o);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* args = Py_BuildValue("(sOO)", fname, arrs, names);
  Py_DECREF(arrs);
  Py_DECREF(names);
  PyObject* r = CallBridge("nd_save", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                            NDArrayHandle** out_arr,
                            mx_uint* out_name_size,
                            const char*** out_names) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* r = CallBridge("nd_load", args);  // [(name|None, nd), ...]
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_loaded.clear();
  g_loaded_name_store.clear();
  g_loaded_names.clear();
  bool any_names = false;
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject* pair = PyList_GetItem(r, i);
    PyObject* name = PyTuple_GetItem(pair, 0);
    PyObject* ndo = PyTuple_GetItem(pair, 1);
    Py_INCREF(ndo);
    g_loaded.push_back(ndo);
    if (name != Py_None) {
      const char* c = PyUnicode_AsUTF8(name);
      if (!c) PyErr_Clear();  // unencodable key -> treated as unnamed
      any_names = any_names || c;
      g_loaded_name_store.push_back(c ? std::string(c) : std::string());
    } else {
      g_loaded_name_store.push_back(std::string());
    }
  }
  Py_DECREF(r);
  for (auto& s : g_loaded_name_store)
    g_loaded_names.push_back(s.empty() ? nullptr : s.c_str());
  *out_size = static_cast<mx_uint>(g_loaded.size());
  *out_arr = g_loaded.data();
  *out_name_size = any_names ? *out_size : 0;
  *out_names = g_loaded_names.data();
  return 0;
}
