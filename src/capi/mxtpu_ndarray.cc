// NDArray + operator-invoke C ABI (see include/mxtpu/c_api.h).
//
// Same architecture as mxtpu_predict.cc: the compute path is XLA via the
// Python runtime, so this library embeds CPython and forwards each call
// to mxnet_tpu/capi_bridge.py.  An NDArrayHandle is an owned PyObject*
// of a framework NDArray; everything else crossing the boundary is raw
// bytes, ints and strings.  Every entry point takes the GIL.

#include <Python.h>

#include "embed_python.h"

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

typedef void* NDArrayHandle;
typedef uint32_t mx_uint;

namespace {

// results that must outlive the call that produced them
thread_local std::vector<mx_uint> g_shape;
thread_local std::vector<NDArrayHandle> g_outputs;
thread_local std::string g_op_names;
thread_local std::vector<NDArrayHandle> g_loaded;
thread_local std::vector<std::string> g_loaded_name_store;
thread_local std::vector<const char*> g_loaded_names;

PyObject* GetBridge() {
  return PyImport_ImportModule("mxnet_tpu.capi_bridge");
}

// Call bridge.<method>(...) with a pre-built args tuple (steals nothing).
PyObject* CallBridge(const char* method, PyObject* args) {
  PyObject* bridge = GetBridge();
  if (!bridge) return nullptr;
  PyObject* fn = PyObject_GetAttrString(bridge, method);
  Py_DECREF(bridge);
  if (!fn) return nullptr;
  PyObject* r = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return r;
}

}  // namespace

MXTPU_API const char* MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int* out) {
  *out = 10301;  // reference parity line (1.3.1)
  return 0;
}

MXTPU_API int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              int dtype, NDArrayHandle* out) {
  (void)dev_id;
  (void)delay_alloc;
  GILGuard gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oii)", shp, dtype, dev_type);
  Py_DECREF(shp);
  PyObject* nd = CallBridge("nd_create", args);
  Py_DECREF(args);
  if (!nd) {
    SetErrorFromPython();
    return -1;
  }
  *out = nd;  // ownership transfers to the handle
  return 0;
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void* data,
                                       size_t size_bytes) {
  GILGuard gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), size_bytes);
  PyObject* args = Py_BuildValue("(OO)",
                                 static_cast<PyObject*>(handle), bytes);
  Py_DECREF(bytes);
  PyObject* r = CallBridge("nd_copy_from_bytes", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                                     size_t size_bytes) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_to_bytes", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    SetErrorFromPython();
    return -1;
  }
  if (static_cast<size_t>(n) > size_bytes) {
    Py_DECREF(r);
    g_last_error = "destination buffer too small";
    return -1;
  }
  std::memcpy(data, buf, n);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                                const mx_uint** out_pdata) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_shape", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(r); ++i)
    g_shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(r, i))));
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(g_shape.size());
  *out_pdata = g_shape.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("nd_dtype", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out_dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXImperativeInvoke(const char* op_name, int num_inputs,
                                 NDArrayHandle* inputs, int* num_outputs,
                                 NDArrayHandle** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals) {
  GILGuard gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject* params = PyDict_New();
  for (int i = 0; i < num_params; ++i) {
    PyObject* v = PyUnicode_FromString(param_vals[i]);
    PyDict_SetItemString(params, param_keys[i], v);  // does not steal
    Py_DECREF(v);
  }
  PyObject* args = Py_BuildValue("(sOO)", op_name, ins, params);
  Py_DECREF(ins);
  Py_DECREF(params);
  PyObject* r = CallBridge("nd_invoke", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_outputs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject* o = PyList_GetItem(r, i);
    Py_INCREF(o);  // each output handle is caller-owned
    g_outputs.push_back(o);
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(g_outputs.size());
  *outputs = g_outputs.data();
  return 0;
}

MXTPU_API int MXListAllOpNames(const char** out_names) {
  GILGuard gil;
  PyObject* args = PyTuple_New(0);
  PyObject* r = CallBridge("nd_list_ops", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  const char* c = PyUnicode_AsUTF8(r);
  g_op_names = c ? c : "";
  Py_DECREF(r);
  *out_names = g_op_names.c_str();
  return 0;
}

MXTPU_API int MXNDArraySave(const char* fname, mx_uint num_args,
                            NDArrayHandle* args_in, const char** keys) {
  GILGuard gil;
  PyObject* arrs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* o = static_cast<PyObject*>(args_in[i]);
    Py_INCREF(o);
    PyList_SetItem(arrs, i, o);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* args = Py_BuildValue("(sOO)", fname, arrs, names);
  Py_DECREF(arrs);
  Py_DECREF(names);
  PyObject* r = CallBridge("nd_save", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                            NDArrayHandle** out_arr,
                            mx_uint* out_name_size,
                            const char*** out_names) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* r = CallBridge("nd_load", args);  // [(name|None, nd), ...]
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  g_loaded.clear();
  g_loaded_name_store.clear();
  g_loaded_names.clear();
  bool any_names = false;
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    PyObject* pair = PyList_GetItem(r, i);
    PyObject* name = PyTuple_GetItem(pair, 0);
    PyObject* ndo = PyTuple_GetItem(pair, 1);
    Py_INCREF(ndo);
    g_loaded.push_back(ndo);
    if (name != Py_None) {
      const char* c = PyUnicode_AsUTF8(name);
      if (!c) PyErr_Clear();  // unencodable key -> treated as unnamed
      any_names = any_names || c;
      g_loaded_name_store.push_back(c ? std::string(c) : std::string());
    } else {
      g_loaded_name_store.push_back(std::string());
    }
  }
  Py_DECREF(r);
  for (auto& s : g_loaded_name_store)
    g_loaded_names.push_back(s.empty() ? nullptr : s.c_str());
  *out_size = static_cast<mx_uint>(g_loaded.size());
  *out_arr = g_loaded.data();
  *out_name_size = any_names ? *out_size : 0;
  *out_names = g_loaded_names.data();
  return 0;
}

// ---------------------------------------------------------------------------
// Symbol + executor surface (reference: src/c_api/c_api_symbolic.cc,
// c_api_executor.cc).  SymbolHandle / ExecutorHandle are owned
// PyObject* like NDArrayHandle; listings marshal as newline-joined
// strings (the MXListAllOpNames convention) to keep the FFI shape
// trivial for any binder.
// ---------------------------------------------------------------------------

typedef void* SymbolHandle;
typedef void* ExecutorHandle;

namespace {
thread_local std::string g_sym_list;
thread_local std::string g_sym_json;
thread_local std::vector<NDArrayHandle> g_bind_args;
thread_local std::vector<NDArrayHandle> g_bind_grads;
thread_local std::vector<NDArrayHandle> g_bind_auxs;
thread_local std::vector<NDArrayHandle> g_exec_outputs;

// shared tail for the two listing-style string returns
int StringResult(PyObject* r, std::string* store, const char** out) {
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  const char* c = PyUnicode_AsUTF8(r);
  if (!c) {
    Py_DECREF(r);
    SetErrorFromPython();
    return -1;
  }
  *store = c;
  Py_DECREF(r);
  *out = store->c_str();
  return 0;
}

// copy a bridge list of (NDArray | None) into caller-visible handles
void HandlesFromList(PyObject* list, std::vector<NDArrayHandle>* dst) {
  dst->clear();
  for (Py_ssize_t i = 0; i < PyList_Size(list); ++i) {
    PyObject* o = PyList_GetItem(list, i);
    if (o == Py_None) {
      dst->push_back(nullptr);
    } else {
      Py_INCREF(o);
      dst->push_back(o);
    }
  }
}
}  // namespace

MXTPU_API int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* r = CallBridge("sym_from_json", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolFree(SymbolHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("sym_to_json", args);
  Py_DECREF(args);
  return StringResult(r, &g_sym_json, out_json);
}

namespace {
int SymList(SymbolHandle handle, const char* which, const char** out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(handle),
                                 which);
  int rc = StringResult(CallBridge("sym_list", args), &g_sym_list, out);
  Py_DECREF(args);
  return rc;
}
}  // namespace

MXTPU_API int MXSymbolListArguments(SymbolHandle h, const char** out) {
  return SymList(h, "arguments", out);
}

MXTPU_API int MXSymbolListAuxiliaryStates(SymbolHandle h,
                                          const char** out) {
  return SymList(h, "aux", out);
}

MXTPU_API int MXSymbolListOutputs(SymbolHandle h, const char** out) {
  return SymList(h, "outputs", out);
}

// Bind a symbol with named input shapes; remaining arg/aux shapes are
// inferred and allocated.  in_args/arg_grads/aux_states receive one
// NEW caller-owned handle per name in list-order (arg_grads entries
// are NULL where grad_req excludes the arg).  The three arrays stay
// valid until the next SimpleBind on the thread.
MXTPU_API int MXExecutorSimpleBind(
    SymbolHandle sym, int dev_type, int dev_id, const char* grad_req,
    mx_uint num_inputs, const char** input_keys,
    const mx_uint* input_shape_data, const mx_uint* input_shape_ndim,
    ExecutorHandle* out, mx_uint* num_in_args, NDArrayHandle** in_args,
    NDArrayHandle** arg_grads, mx_uint* num_aux,
    NDArrayHandle** aux_states) {
  GILGuard gil;
  PyObject* keys = PyList_New(num_inputs);
  PyObject* shapes = PyList_New(num_inputs);
  size_t off = 0;
  for (mx_uint i = 0; i < num_inputs; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
    PyObject* shp = PyTuple_New(input_shape_ndim[i]);
    for (mx_uint d = 0; d < input_shape_ndim[i]; ++d)
      PyTuple_SetItem(shp, d,
                      PyLong_FromUnsignedLong(input_shape_data[off + d]));
    off += input_shape_ndim[i];
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* args = Py_BuildValue("(OiisOO)",
                                 static_cast<PyObject*>(sym), dev_type,
                                 dev_id, grad_req, keys, shapes);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  PyObject* r = CallBridge("exec_simple_bind", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* cex = PyTuple_GetItem(r, 0);
  Py_INCREF(cex);
  HandlesFromList(PyTuple_GetItem(r, 1), &g_bind_args);
  HandlesFromList(PyTuple_GetItem(r, 2), &g_bind_grads);
  HandlesFromList(PyTuple_GetItem(r, 3), &g_bind_auxs);
  Py_DECREF(r);
  *out = cex;
  *num_in_args = static_cast<mx_uint>(g_bind_args.size());
  *in_args = g_bind_args.data();
  *arg_grads = g_bind_grads.data();
  *num_aux = static_cast<mx_uint>(g_bind_auxs.size());
  *aux_states = g_bind_auxs.data();
  return 0;
}

MXTPU_API int MXExecutorFree(ExecutorHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXExecutorForward(ExecutorHandle handle, int is_train) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle),
                                 is_train);
  PyObject* r = CallBridge("exec_forward", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);  // outputs re-fetched via MXExecutorOutputs
  return 0;
}

MXTPU_API int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                                 NDArrayHandle* head_grads) {
  GILGuard gil;
  PyObject* grads = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i) {
    PyObject* o = static_cast<PyObject*>(head_grads[i]);
    Py_INCREF(o);
    PyList_SetItem(grads, i, o);
  }
  PyObject* args = Py_BuildValue("(OO)", static_cast<PyObject*>(handle),
                                 grads);
  Py_DECREF(grads);
  PyObject* r = CallBridge("exec_backward", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                                NDArrayHandle** outputs) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("exec_outputs", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  HandlesFromList(r, &g_exec_outputs);
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(g_exec_outputs.size());
  *outputs = g_exec_outputs.data();
  return 0;
}

// ---------------------------------------------------------------------------
// KVStore surface (reference: src/c_api/c_api.cc MXKVStoreCreate /
// Init / Push / Pull string-key variants + rank/size).  KVStoreHandle
// is an owned PyObject* like the other handles.
// ---------------------------------------------------------------------------

typedef void* KVStoreHandle;

namespace {
thread_local std::string g_kv_type;

// (keys, NDArray handles) -> bridge args (list[str], list[NDArray]);
// NULL on a bad (non-UTF-8) key, with the Python error set
PyObject* KeyedArrays(const char** keys, NDArrayHandle* vals, mx_uint n) {
  PyObject* ks = PyList_New(n);
  PyObject* vs = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject* k = PyUnicode_FromString(keys[i]);
    if (!k) {
      Py_DECREF(ks);
      Py_DECREF(vs);
      return nullptr;
    }
    PyList_SetItem(ks, i, k);
    PyObject* o = static_cast<PyObject*>(vals[i]);
    Py_INCREF(o);
    PyList_SetItem(vs, i, o);
  }
  PyObject* pair = PyTuple_Pack(2, ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  return pair;
}

// one keyed bridge call; priority < 0 means the method takes none
int KvKeyedCall(const char* method, KVStoreHandle h, mx_uint n,
                const char** keys, NDArrayHandle* vals, int priority) {
  GILGuard gil;
  PyObject* ka = KeyedArrays(keys, vals, n);
  if (!ka) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* args = priority < 0
      ? Py_BuildValue("(OOO)", static_cast<PyObject*>(h),
                      PyTuple_GetItem(ka, 0), PyTuple_GetItem(ka, 1))
      : Py_BuildValue("(OOOi)", static_cast<PyObject*>(h),
                      PyTuple_GetItem(ka, 0), PyTuple_GetItem(ka, 1),
                      priority);
  Py_DECREF(ka);
  PyObject* r = CallBridge(method, args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

// int-valued single-handle bridge call (kvstore rank/size, iterator
// next/pad)
int KvIntResult(const char* method, void* h, int* out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  PyObject* r = CallBridge(method, args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}
}  // namespace

MXTPU_API int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* r = CallBridge("kv_create", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out = r;
  return 0;
}

MXTPU_API int MXKVStoreFree(KVStoreHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXKVStoreInitEx(KVStoreHandle h, mx_uint num,
                              const char** keys, NDArrayHandle* vals) {
  return KvKeyedCall("kv_init", h, num, keys, vals, /*priority=*/-1);
}

MXTPU_API int MXKVStorePushEx(KVStoreHandle h, mx_uint num,
                              const char** keys, NDArrayHandle* vals,
                              int priority) {
  return KvKeyedCall("kv_push", h, num, keys, vals, priority);
}

MXTPU_API int MXKVStorePullEx(KVStoreHandle h, mx_uint num,
                              const char** keys, NDArrayHandle* outs,
                              int priority) {
  return KvKeyedCall("kv_pull", h, num, keys, outs, priority);
}

MXTPU_API int MXKVStoreGetType(KVStoreHandle h, const char** out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  PyObject* r = CallBridge("kv_type", args);
  Py_DECREF(args);
  return StringResult(r, &g_kv_type, out);
}

MXTPU_API int MXKVStoreGetRank(KVStoreHandle h, int* out) {
  return KvIntResult("kv_rank", h, out);
}

MXTPU_API int MXKVStoreGetGroupSize(KVStoreHandle h, int* out) {
  return KvIntResult("kv_group_size", h, out);
}

// ---------------------------------------------------------------------------
// DataIter surface (reference: src/c_api/c_api.cc MXListDataIters /
// MXDataIterCreateIter / Next / BeforeFirst / GetData / GetLabel /
// GetPadNum).  DataIterHandle is an owned PyObject* like the others;
// creation takes string key/value params exactly like the reference's
// creator entry point.
// ---------------------------------------------------------------------------

typedef void* DataIterHandle;

namespace {
thread_local std::string g_iter_names;

int IterNdResult(const char* method, DataIterHandle h,
                 NDArrayHandle* out) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(h));
  PyObject* r = CallBridge(method, args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out = r;  // new caller-owned NDArray reference
  return 0;
}

}  // namespace

MXTPU_API int MXListDataIters(const char** out_names) {
  GILGuard gil;
  PyObject* args = PyTuple_New(0);
  PyObject* r = CallBridge("io_list_iters", args);
  Py_DECREF(args);
  return StringResult(r, &g_iter_names, out_names);
}

MXTPU_API int MXDataIterCreateIter(const char* name, mx_uint num_params,
                                   const char** keys, const char** vals,
                                   DataIterHandle* out) {
  GILGuard gil;
  PyObject* ks = PyList_New(num_params);
  PyObject* vs = PyList_New(num_params);
  for (mx_uint i = 0; i < num_params; ++i) {
    PyObject* k = PyUnicode_FromString(keys[i]);
    PyObject* v = k ? PyUnicode_FromString(vals[i]) : nullptr;
    if (!k || !v) {
      Py_XDECREF(k);
      Py_DECREF(ks);
      Py_DECREF(vs);
      SetErrorFromPython();
      return -1;
    }
    PyList_SetItem(ks, i, k);
    PyList_SetItem(vs, i, v);
  }
  PyObject* args = Py_BuildValue("(sOO)", name, ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyObject* r = CallBridge("io_create", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  *out = r;
  return 0;
}

MXTPU_API int MXDataIterFree(DataIterHandle handle) {
  GILGuard gil;
  Py_XDECREF(static_cast<PyObject*>(handle));
  return 0;
}

MXTPU_API int MXDataIterNext(DataIterHandle handle, int* out) {
  return KvIntResult("io_next", handle, out);
}

MXTPU_API int MXDataIterBeforeFirst(DataIterHandle handle) {
  GILGuard gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallBridge("io_before_first", args);
  Py_DECREF(args);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXDataIterGetData(DataIterHandle handle,
                                NDArrayHandle* out) {
  return IterNdResult("io_data", handle, out);
}

MXTPU_API int MXDataIterGetLabel(DataIterHandle handle,
                                 NDArrayHandle* out) {
  return IterNdResult("io_label", handle, out);
}

MXTPU_API int MXDataIterGetPadNum(DataIterHandle handle, int* out) {
  return KvIntResult("io_pad", handle, out);
}
