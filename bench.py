"""Round benchmark: ResNet-50 synthetic-data training throughput + MFU.

Mirrors the reference harness
(`example/image-classification/benchmark_score.py`, methodology of
`docs/faq/perf.md:42-219`): synthetic NCHW batch, warmup, timed steps.
Prints ONE JSON line:
  {"metric": ..., "value": img/s, "unit": "images/sec", "vs_baseline": x}
vs_baseline is against the reference's strongest published ResNet-50
training number (V100 bs=128, 363.69 img/s, docs/faq/perf.md:219).

Measurement notes (learned the hard way on this image):
 * ``jax.Array.block_until_ready`` does NOT reliably wait for execution
   over the axon TPU tunnel — only a host readback does.  All timing
   here forces a scalar readback; buffer donation chains step N+1 on
   step N's outputs, so reading the final loss serializes the whole
   timed window.
 * The MFU denominator is probed EMPIRICALLY: a chain of large bf16
   matmuls (data-dependent, so they cannot overlap) timed with the
   same readback discipline.  Hardcoded datasheet numbers are reported
   alongside for reference but the probe is the denominator.  MFU is
   asserted to lie in (0, 1].
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

# Platform selection + tunnel-health guard.  An explicitly-CPU
# JAX_PLATFORMS is honored directly (local testing only; set
# BENCH_ALLOW_CPU=1 to acknowledge).  For ANY TPU-capable target
# (including the environment's default JAX_PLATFORMS=axon) probe tunnel
# health first: a wedged axon tunnel hangs jax compute FOREVER (observed
# after killing in-flight TPU work), and a half-recovered tunnel answers
# device discovery while compute still hangs — so the probe runs an
# actual computation with a host readback, in a child process.
#
# A failed probe is retried with backoff for up to ~10 minutes; if the
# TPU never answers, bench exits NONZERO without printing a result line.
# A CPU number must never masquerade as the round artifact (that is
# exactly what round 3 shipped).


def _probe_tpu_once(deadline_s):
    """One tunnel-health attempt: real compute + host readback in a
    child, ABANDONED (not reaped) on deadline.

    subprocess.run(timeout=...) is NOT safe here — a child stuck in the
    wedged TPU driver sits in uninterruptible sleep, and run() blocks
    forever trying to reap it after SIGKILL (observed: 18 min of wall
    for 3 s of user time).  Poll and abandon instead.
    """
    import subprocess
    probe = subprocess.Popen(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp; "
         "print(int(jnp.sum(jnp.ones((256, 256)))))"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if probe.poll() is not None:
            out = probe.stdout.read() or ""
            lines = out.strip().splitlines()
            # last stdout line is the value (earlier lines may be banners)
            return (probe.returncode == 0 and bool(lines)
                    and lines[-1].isdigit())
        time.sleep(1)
    try:
        probe.kill()  # may not die (D state); do NOT wait on it
    except Exception:
        pass
    return False


def _ensure_platform():
    """Select/validate the platform; exits the process on an unusable
    target.  Called from main() so that ``import bench`` (tools reuse
    `_probe_tpu_once` / `_probe_peak_flops`) has NO side effects."""
    target = os.environ.get("JAX_PLATFORMS", "")
    if target.strip().lower() == "cpu":
        if not os.environ.get("BENCH_ALLOW_CPU"):
            print("bench: JAX_PLATFORMS=cpu without BENCH_ALLOW_CPU=1 — "
                  "refusing to produce a CPU number as the bench artifact",
                  file=sys.stderr)
            sys.exit(3)
        import jax
        jax.config.update("jax_platforms", "cpu")
        return
    healthy = False
    # Default ~10.5 min budget: 150 s first attempt (covers slow first
    # compile of the probe), then shorter retries with growing pauses to
    # ride out a tunnel restart.  BENCH_PROBE_BUDGET_S extends the total
    # wait — a round wrapper that wants to camp on a dead tunnel for an
    # hour sets it; past the listed attempts we keep cycling 90 s probes
    # with 120 s pauses until the budget runs out.
    budget_s = float(os.environ.get("BENCH_PROBE_BUDGET_S", "630"))
    deadline = time.time() + budget_s
    attempts = [(150, 30), (90, 60), (90, 120)]
    attempt = 0
    while True:
        probe_s, pause_s = attempts[attempt] if attempt < len(attempts) \
            else (90, 120)
        healthy = _probe_tpu_once(min(probe_s, max(30, deadline - time.time())))
        if healthy or time.time() + pause_s + 30 > deadline:
            break
        print("bench: TPU health probe attempt %d failed; retrying in "
              "%d s" % (attempt + 1, pause_s), file=sys.stderr)
        time.sleep(pause_s)
        attempt += 1
    if not healthy:
        print("bench: TPU tunnel never answered a real computation — "
              "exiting nonzero (no CPU fallback for the round artifact)",
              file=sys.stderr)
        sys.exit(2)
    import jax
    if target:
        jax.config.update("jax_platforms", target)

BASELINE_IMG_S = 363.69  # V100 bs=128 training, docs/faq/perf.md:219

# bf16 datasheet peaks (reported for context only; the empirical probe
# below is the MFU denominator)
_DATASHEET = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _datasheet_peak(dev):
    kind = getattr(dev, "device_kind", "")
    for k, v in _DATASHEET.items():
        if kind.startswith(k):
            return v
    return None


def _probe_peak_flops(iters=40, n=8192):
    """Achievable bf16 matmul FLOP/s: chained (serialized) matmuls,
    timed to a scalar host readback."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)

    def chain(a, b, length):
        def body(c, _):
            return jnp.tanh(c @ b), None
        c, _ = jax.lax.scan(body, a, None, length=length)
        return jnp.sum(c.astype(jnp.float32))

    short = jax.jit(lambda a, b: chain(a, b, iters // 4))
    full = jax.jit(lambda a, b: chain(a, b, iters))
    float(short(a, b))  # warm
    float(full(a, b))
    t0 = time.perf_counter()
    float(short(a, b))
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(full(a, b))
    t_full = time.perf_counter() - t0
    # subtracting the short run removes fixed dispatch/sync latency
    per = (t_full - t_short) / (iters - iters // 4)
    return 2.0 * n ** 3 / per


def _probe_peak_bw(mb=256, iters=16):
    """Achievable HBM/memory bandwidth (bytes/s): a chained
    elementwise add over an *mb*-megabyte f32 buffer — each scan step
    reads and writes the whole buffer (2x its size in traffic) and
    depends on the previous one, same short-vs-full readback
    discipline as the flops probe.  This is the roofline denominator
    the MFU decompose classifies ops against."""
    import jax
    import jax.numpy as jnp

    n = max(1, int(mb * 1e6) // 4)
    x = jnp.ones((n,), jnp.float32)

    def chain(x, length):
        def body(c, _):
            return c + jnp.float32(1.0), None
        c, _ = jax.lax.scan(body, x, None, length=length)
        return jnp.sum(c)

    short = jax.jit(lambda x: chain(x, iters // 4))
    full = jax.jit(lambda x: chain(x, iters))
    float(short(x))  # warm
    float(full(x))
    t0 = time.perf_counter()
    float(short(x))
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(full(x))
    t_full = time.perf_counter() - t0
    per = (t_full - t_short) / (iters - iters // 4)
    if per <= 0:
        # a GC pause / scheduler hiccup during the millisecond-scale
        # short run can make the delta non-positive; a None denominator
        # degrades the decompose to flops-share-only (cost_table
        # accepts it) instead of crashing the run or silently
        # classifying every op against a negative balance point
        print("bench: bandwidth probe degenerate (short %.4fs >= full "
              "%.4fs) — no roofline denominator" % (t_short, t_full),
              file=sys.stderr)
        return None
    return 2.0 * n * 4 / per


def timed_resnet_train(batch, image, remat, iters, scan_n, warmup=2,
                       optimizer="lbsgd", multi_precision=True,
                       coalesce_small=None, momentum=0.9, stem=None):
    """Build the north-star ResNet-50 trainer and time its step.

    This is THE measurement harness (tools/mfu_sweep.py reuses it):
    steps are scanned inside ONE dispatch per host call — the idiomatic
    TPU training-loop shape, which also keeps per-call tunnel latency
    out of the device number — and the timed window is forced complete
    by a host readback of the final loss (donation chains the steps;
    `block_until_ready` does NOT wait over the tunnel).

    Returns a dict with img_s / dt / iters / flops_per_step /
    final_loss."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    dev = jax.devices()[0]
    # BENCH_STEM=s2d swaps the 7x7 stem for the space-to-depth variant
    # (model_zoo SpaceToDepthStem — the MXU-utilization stem)
    stem = stem or os.environ.get("BENCH_STEM") or "conv7"
    net = vision.get_model("resnet50_v1", classes=1000, stem=stem)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    # north-star config: bf16 compute weights + f32 masters + LARS
    # (docs/faq/perf.md fp16 ≈ 2x fp32 sanity ratio applies to bf16)
    opt_params = {"learning_rate": 0.1, "eta": 0.001}
    if momentum:
        opt_params["momentum"] = momentum
    trainer = ParallelTrainer(
        net, loss, optimizer=optimizer, optimizer_params=opt_params,
        mesh=make_mesh({"dp": 1}, [dev]),
        multi_precision=multi_precision, remat=remat,
        coalesce_small=coalesce_small)

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, 3, image, image).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))

    r = timed_train_steps(trainer, x, y, iters, scan_n, warmup)
    if not r["flops_per_step"]:
        # analytic fwd+bwd ResNet-50, scaled from the 224x224 figure
        r["flops_per_step"] = 3 * 4.089e9 * batch * (image / 224.0) ** 2
    r["img_s"] = batch * r["iters"] / r["dt"]
    return r


def timed_train_steps(trainer, x, y, iters, scan_n, warmup=2):
    """Shared training-step timing harness (tools/benchmark_lm.py and
    timed_resnet_train use it): scan_n steps chained by donation inside
    ONE jit per host call, timed to a host readback of the final loss.
    Returns {dt, iters, flops_per_step (None if cost analysis
    unavailable), final_loss}."""
    import jax
    import jax.numpy as jnp

    for _ in range(max(1, warmup)):
        l = trainer.fit_batch(x, y)
    float(np.asarray(l))  # forced readback

    step = trainer._step_fn

    def multi(params, opt_state, aux, xb, yb, key, lr, t):
        def body(carry, i):
            p, s, a = carry
            p, s, a, l = step(p, s, a, xb, yb,
                              jax.random.fold_in(key, i), lr, t)
            return (p, s, a), l
        (p, s, a), ls = jax.lax.scan(
            body, (params, opt_state, aux), jnp.arange(scan_n))
        return p, s, a, ls[-1]

    multi_j = jax.jit(multi, donate_argnums=(0, 1, 2))
    xd = x._data
    if trainer.multi_precision and jnp.issubdtype(xd.dtype, jnp.floating):
        xd = xd.astype(jnp.bfloat16)
    yd = y._data
    # the trainer's OWN configured hyperparameters — this harness is
    # shared (benchmark_lm runs lr=0.01), hard-coding resnet's 0.1
    # would time steps the model never takes
    lr = np.float32(trainer._current_lr())
    t = np.int32(trainer._num_update + 1)
    p, s, a = trainer._params, trainer._opt_state, trainer._aux
    p, s, a, l = multi_j(p, s, a, xd, yd, jax.random.PRNGKey(0), lr, t)
    float(np.asarray(l))  # warm the scanned executable

    t0 = time.perf_counter()
    for it in range(max(1, iters // scan_n)):
        p, s, a, l = multi_j(p, s, a, xd, yd,
                             jax.random.PRNGKey(it + 1), lr, t)
    final_loss = float(np.asarray(l))  # donation chains all timed steps
    dt = time.perf_counter() - t0
    n = max(1, iters // scan_n) * scan_n
    trainer._params, trainer._opt_state, trainer._aux = p, s, a

    # exact per-step FLOPs from the compiled program when available;
    # the lowered StableHLO text rides along for the per-op MFU
    # decompose (observability.costs — bench --decompose and the
    # "decompose" key of the round artifact)
    flops = None
    hlo_text = None
    try:
        low = trainer._step_fn.lower(
            trainer._params, trainer._opt_state, trainer._aux,
            trainer._device_batch(x._data), y._data,
            jax.random.PRNGKey(0), lr, t)
        try:
            hlo_text = low.as_text()
        except Exception:
            hlo_text = None
        ca = low.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if ca and "flops" in ca:
            flops = float(ca["flops"])
    except Exception:
        pass
    return {"dt": dt, "iters": n, "flops_per_step": flops,
            "final_loss": final_loss, "hlo_text": hlo_text}


def timed_scan_forward(eval_fn, params, aux, xd, extra, scan_n, iters,
                       warmup=2):
    """Shared forward-timing harness (tools/benchmark_score.py reuses
    it): scan_n forwards chained through a carry inside ONE jit — the
    data depends on the carry so XLA cannot hoist the loop-invariant
    computation — timed to a host readback (`block_until_ready` does
    not wait over the tunnel).

    ``extra`` maps additional eval-graph inputs (e.g. label0).
    Returns (dt_seconds, iters_run, flops_per_call_or_None)."""
    import jax
    import jax.numpy as jnp

    def multi(params, aux, xb, key):
        def body(c, i):
            amap = dict(params)
            amap["data0"] = xb + (c * 0).astype(xb.dtype)
            amap.update(extra)
            outs, _ = eval_fn(amap, aux, jax.random.fold_in(key, i))
            return c + jnp.mean(outs[0].astype(jnp.float32)), None
        s, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(scan_n))
        return s

    mj = jax.jit(multi)
    for _ in range(max(1, warmup)):
        float(np.asarray(mj(params, aux, xd, jax.random.PRNGKey(0))))
    t0 = time.perf_counter()
    for it in range(max(1, iters // scan_n)):
        s = mj(params, aux, xd, jax.random.PRNGKey(it + 1))
    float(np.asarray(s))  # device FIFO: the last readback drains all
    dt = time.perf_counter() - t0
    n = max(1, iters // scan_n) * scan_n
    flops = None
    try:
        ca = mj.lower(params, aux, xd,
                      jax.random.PRNGKey(0)).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if ca and "flops" in ca:
            flops = float(ca["flops"]) / scan_n
    except Exception:
        pass
    return dt, n, flops


def timed_resnet_fwd(batch, image, iters, scan_n, warmup=2,
                     multi_precision=True):
    """Training-mode FORWARD only, same scan/readback discipline as
    timed_resnet_train — the fwd/bwd/optimizer decomposition baseline
    for tools/mfu_sweep.py --decompose."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    dev = jax.devices()[0]
    net = vision.get_model("resnet50_v1", classes=1000)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = ParallelTrainer(
        net, loss, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1},
        mesh=make_mesh({"dp": 1}, [dev]),
        multi_precision=multi_precision)

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, 3, image, image).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))
    trainer.fit_batch(x, y)  # build + gather state

    xd = trainer._device_batch(x._data)
    dt, n, flops = timed_scan_forward(
        trainer._eval, trainer._params, trainer._aux, xd,
        {"label0": y._data}, scan_n, iters, warmup)
    if not flops:
        # analytic fwd ResNet-50, scaled from the 224x224 figure
        flops = 4.089e9 * batch * (image / 224.0) ** 2
    return {"img_s": batch * n / dt, "dt": dt, "iters": n,
            "flops_per_step": flops}


def compare_update_paths(n_layers=30, dim=64, batch=32, steps=30,
                         optimizer="sgd", opt_params=None):
    """``--compare-update-paths``: fused ``forward_backward_update``
    (one donated XLA program per step) vs the legacy
    forward_backward + per-parameter Updater loop, on a deep synthetic
    MLP (2*n_layers+2 parameters — launch-overhead bound, so the
    per-step dispatch count is what's measured).  Runs anywhere; on CPU
    it is the fused-step acceptance microbench.  Prints one JSON line
    and returns the dict."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.io import DataBatch

    def build():
        data = sym.var("data")
        net = data
        for i in range(n_layers):
            net = sym.FullyConnected(net, num_hidden=dim, name="l%d" % i)
            net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=4, name="out")
        return sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, dim).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))
    data_batch = DataBatch(data=[x], label=[y])
    params = dict(opt_params or {"learning_rate": 0.01, "momentum": 0.9})

    def run(fused):
        prior = os.environ.get("MXNET_MODULE_FUSED_STEP")
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1" if fused else "0"
        try:
            mod = mx.Module(build(), context=mx.cpu())
            mod.bind([("data", (batch, dim))],
                     [("softmax_label", (batch,))])
            mod.init_params(mx.init.Xavier())
            mod.init_optimizer(optimizer=optimizer,
                               optimizer_params=dict(params))
            for _ in range(3):                       # warmup/compile
                mod.forward_backward_update(data_batch)
            mod.get_outputs()[0].asnumpy()
            t0 = time.perf_counter()
            for _ in range(steps):
                mod.forward_backward_update(data_batch)
            # readbacks drain the async chain before the clock stops
            mod.get_outputs()[0].asnumpy()
            mod._exec_group.execs[0].arg_dict["l0_weight"].asnumpy()
            return steps / (time.perf_counter() - t0)
        finally:
            if prior is None:
                os.environ.pop("MXNET_MODULE_FUSED_STEP", None)
            else:
                os.environ["MXNET_MODULE_FUSED_STEP"] = prior

    legacy = run(False)
    fused = run(True)
    out = {
        "metric": "fused_vs_legacy_update_paths",
        "fused_steps_per_s": round(fused, 2),
        "legacy_steps_per_s": round(legacy, 2),
        "speedup": round(fused / legacy, 3),
        "n_params": 2 * n_layers + 2,
        "optimizer": optimizer,
        "batch_size": batch,
    }
    print(json.dumps(out))
    return out


class _SlowDecodeIter:
    """Host-bound iterator simulator for ``--compare-input-paths``: a
    DataIter-shaped source whose ``next()`` burns *decode_s* seconds
    of host time (the stand-in for jpeg decode / augmentation) and
    hands out HOST numpy batches — exactly what a decode pipeline
    produces.  The serial path then pays the host→device transfer
    inside the step loop; the pipelined path pays it on the
    DevicePrefetcher's producer thread."""

    def __init__(self, data, label, batch_size, decode_s):
        self.batch_size = batch_size
        self.decode_s = decode_s
        n = (data.shape[0] // batch_size) * batch_size
        self._data = [data[i:i + batch_size]
                      for i in range(0, n, batch_size)]
        self._label = [label[i:i + batch_size]
                       for i in range(0, n, batch_size)]
        self._cursor = 0

    @property
    def provide_data(self):
        from mxnet_tpu.io import DataDesc
        return [DataDesc("data", self._data[0].shape,
                         self._data[0].dtype)]

    @property
    def provide_label(self):
        from mxnet_tpu.io import DataDesc
        return [DataDesc("softmax_label", self._label[0].shape,
                         self._label[0].dtype)]

    def reset(self):
        self._cursor = 0

    def next(self):
        from mxnet_tpu.io import DataBatch
        if self._cursor >= len(self._data):
            raise StopIteration
        time.sleep(self.decode_s)
        i = self._cursor
        self._cursor += 1
        return DataBatch(data=[self._data[i]], label=[self._label[i]],
                         pad=0)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def state_dict(self):
        return {"type": type(self).__name__, "cursor": self._cursor}

    def load_state(self, state):
        self._cursor = int(state["cursor"])


def compare_input_paths(batch=128, dim=128, hidden=768, n_layers=8,
                        steps=16, depth=3, lag=2):
    """``--compare-input-paths``: serial input path (host decode +
    device_put inside the step loop, guard readback blocking every
    step) vs the pipelined path (``DevicePrefetcher`` ring +
    ``MXNET_GUARD_READBACK_LAG`` async guard accounting), on a
    synthetic host-bound iterator whose decode time X is calibrated to
    the measured device step time Y.  Serial pays ≈ X+Y per step; the
    pipelined steady state pays ≈ max(X, Y) — decode and transfer run
    on the producer thread while the device computes, and the host
    dispatches step N+1 while step N runs.  Runs on CPU by design (a
    dispatch-overlap measurement, like --compare-update-paths).
    Prints one BENCH-schema JSON line (with ``input_stall_share``) and
    returns the dict; ``overlap_ok`` asserts pipelined < 0.7×serial."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.io import DevicePrefetcher
    from mxnet_tpu.observability import metrics as _obs_metrics

    rng = np.random.RandomState(0)
    n = batch * (steps + depth + 12)
    X_data = rng.randn(n, dim).astype(np.float32)
    Y_data = rng.randint(0, 8, (n,)).astype(np.float32)

    def build():
        mx.random.seed(7)
        data = sym.var("data")
        net = data
        for i in range(n_layers):
            net = sym.FullyConnected(net, num_hidden=hidden,
                                     name="l%d" % i)
            net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=8, name="out")
        net = sym.SoftmaxOutput(net, name="softmax")
        mod = mx.Module(net, context=mx.cpu())
        mod.bind([("data", (batch, dim))], [("softmax_label", (batch,))])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
        # the guard's skip-counter readback is the per-step host sync
        # the async path amortizes (see docs/perf_input_pipeline.md)
        mod.set_nonfinite_guard(max_consecutive=0)
        return mod

    def fresh_iter(decode_s):
        return _SlowDecodeIter(X_data, Y_data, batch, decode_s)

    prior = os.environ.get("MXNET_GUARD_READBACK_LAG")

    def set_lag(v):
        if v:
            os.environ["MXNET_GUARD_READBACK_LAG"] = str(v)
        else:
            os.environ.pop("MXNET_GUARD_READBACK_LAG", None)

    try:
        # -- calibrate Y: the serial loop at ZERO decode time --------
        # Y here is everything the serial consumer pays per step
        # besides the simulated decode: the iterator's host batch
        # conversion + the guarded step with its synchronous readback.
        # Calibrating on the REAL loop (not a warm reused batch, whose
        # puts are elided) makes X track what the machine actually
        # does under its current CPU shares.
        set_lag(0)
        mod = build()
        it0 = fresh_iter(0.0)
        for _ in range(3):
            mod.forward_backward_update(it0.next())   # compile + settle
        ys = []
        for _ in range(7):
            t0 = time.perf_counter()
            mod.forward_backward_update(it0.next())
            ys.append(time.perf_counter() - t0)
        step_s = sorted(ys)[len(ys) // 2]
        # X ≈ 1.3Y: the sleep dominates the producer's period (its
        # conversion work contends with XLA's compute threads on
        # small-core hosts), while max(X,Y)/(X+Y) stays near its 0.5
        # floor; the 10 ms floor keeps scheduler jitter second-order
        decode_s = max(1.3 * step_s, 0.010)

        # -- serial: decode + put + blocking readback per step -------
        mod = build()
        it = fresh_iter(decode_s)
        for _ in range(3):
            mod.forward_backward_update(it.next())   # compile + settle
        t0 = time.perf_counter()
        for _ in range(steps):
            mod.forward_backward_update(it.next())
        serial_dt = time.perf_counter() - t0         # guard drains each

        # -- pipelined: device ring + bounded-lag readback -----------
        set_lag(lag)
        mod = build()
        pf = DevicePrefetcher(fresh_iter(decode_s), depth=depth)
        try:
            for _ in range(3 + depth):               # compile + fill ring
                mod.forward_backward_update(pf.next())
            wait_hist = _obs_metrics.REGISTRY.get("input_wait_seconds")
            wait0 = wait_hist.sum
            t0 = time.perf_counter()
            for _ in range(steps):
                mod.forward_backward_update(pf.next())
            # the timed window is only honest once the in-flight lag
            # steps have drained on-device
            mod.drain_guard_readbacks()
            pipe_dt = time.perf_counter() - t0
            stall_share = (wait_hist.sum - wait0) / pipe_dt
        finally:
            pf.close()
    finally:
        if prior is None:
            os.environ.pop("MXNET_GUARD_READBACK_LAG", None)
        else:
            os.environ["MXNET_GUARD_READBACK_LAG"] = prior

    serial_per = serial_dt / steps
    pipe_per = pipe_dt / steps
    out = {
        "metric": "input_pipeline_overlap",
        "value": round(steps / pipe_dt, 2),
        "unit": "steps/sec",
        "serial_steps_per_s": round(steps / serial_dt, 2),
        "pipelined_steps_per_s": round(steps / pipe_dt, 2),
        "speedup": round(serial_per / pipe_per, 3),
        "decode_ms": round(decode_s * 1e3, 3),
        "step_ms": round(step_s * 1e3, 3),
        "serial_ms_per_step": round(serial_per * 1e3, 3),
        "pipelined_ms_per_step": round(pipe_per * 1e3, 3),
        "input_stall_share": round(stall_share, 4),
        "prefetch_depth": depth,
        "guard_readback_lag": lag,
        "batch_size": batch,
        # serial ≈ X+Y, pipelined steady state ≈ max(X,Y): the overlap
        # proof the CI smoke stage asserts
        "overlap_ok": pipe_per < 0.7 * serial_per,
    }
    print(json.dumps(out))
    return out


def _percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (exact — serving
    SLOs are quoted on real request latencies, not histogram bounds)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def serve_bench(hidden=256, dim=64, classes=16,
                closed_threads=8, closed_requests=40,
                open_rate=150.0, open_seconds=2.0, max_wait_ms=1.0,
                record_trace=None, trace=None, quantize=None):
    """``--serve``: load test of the compiled serving subsystem
    (mxnet_tpu/serve): one warm-compiled model behind the dynamic
    batcher, driven closed-loop (N threads, back-to-back requests —
    the throughput ceiling) and open-loop (fixed arrival rate — the
    latency distribution under load, which closed-loop hides by
    coordinated omission).  Mixed request sizes (1-4 rows) exercise
    the coalescing + padding path.  Prints ONE BENCH-schema JSON line
    with p50/p99 latency and throughput and returns the dict.

    ``--record-trace PATH`` serializes the open-loop arrival schedule
    (request sizes + offsets) as an autotune trace;
    ``--trace PATH`` replays a recorded trace as the open loop
    instead of the synthetic grid — the same load the autotuner
    scored, so bench numbers and tuning artifacts are comparable.
    When a ``MXNET_TUNING_STORE`` entry exists for model "bench", the
    hand-picked ladder/window defaults are NOT passed, so the tuned
    config applies (precedence: env > store > default) and the
    ``tuning`` field reports what was picked up."""
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import serve, sym
    from mxnet_tpu.autotune import trace as _at
    from mxnet_tpu.autotune.store import lookup as _at_lookup

    tr = None
    if trace is not None:
        tr = _at.Trace.load(trace)
        if tr.kind != "serve":
            raise ValueError("bench --serve needs a serve trace, got "
                             "kind=%r" % tr.kind)
        dim = int(tr.meta.get("dim", dim))

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="sfc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="sfc2")
    net = sym.softmax(net)
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=(1, dim))
    params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}

    registry = serve.ModelRegistry()
    # with a tuned-store entry for "bench", leave ladder/window unset
    # so the tuning applies (env > store > default); otherwise use the
    # bench's hand-picked defaults
    tuned = _at_lookup("bench", "serve")
    ladder = None if tuned else \
        serve.BucketLadder(batches=(1, 2, 4, 8, 16))
    # --quantize int8|int8-weight-only: serve the post-training-
    # quantized model instead (calibrated on traffic-shaped batches,
    # accuracy-gated at load — docs/quantization.md); the bench line
    # then reports the quantization section next to the latencies so
    # fp32 and int8 artifacts are comparable at a glance
    quant_kw = {}
    if quantize:
        quant_kw = {"quantize": quantize,
                    "calib_batches": [rs.randn(4, dim).astype(np.float32)
                                      for _ in range(8)]}
    t0 = time.perf_counter()
    pred = registry.load("bench", net, params,
                         data_shapes={"data": (1, dim)}, ladder=ladder,
                         **quant_kw)
    warm_s = time.perf_counter() - t0
    batcher = registry.batcher(
        "bench", max_wait_ms=None if tuned else max_wait_ms)
    compiles_after_warm = pred.compile_count

    reqs = [rs.randn(rs.randint(1, 5), dim).astype(np.float32)
            for _ in range(64)]

    # -- closed loop: threads issue back-to-back ------------------------
    lat_closed = []
    worker_errors = []
    lat_lock = threading.Lock()

    def worker(tid):
        mine = []
        try:
            for i in range(closed_requests):
                x = reqs[(tid * closed_requests + i) % len(reqs)]
                t0 = time.monotonic()
                batcher.submit(x).result(60)
                mine.append(time.monotonic() - t0)
        except Exception as exc:
            with lat_lock:
                worker_errors.append("worker %d: %r" % (tid, exc))
        finally:
            with lat_lock:
                lat_closed.extend(mine)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(closed_threads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    closed_dt = time.monotonic() - t0
    if worker_errors:
        # a failed/timed-out request would silently skew the report
        raise RuntimeError("serve bench closed loop failed: %s"
                           % "; ".join(worker_errors[:3]))
    closed_n = closed_threads * closed_requests

    # -- open loop: fixed arrival rate ----------------------------------
    if tr is not None:
        # replay the recorded trace — identical offsets + request
        # sizes the autotuner scored, payloads rematerialized from
        # the trace seed
        records, open_dt = _at.replay(
            tr, lambda x, _i: batcher.submit(x))
        for _slot, _t_sub, fut in records:
            fut.result(60)
        lat_open = [fut._t_resolved - t_sub
                    for _slot, t_sub, fut in records]
        n_open = len(records)
        open_rate = round((n_open - 1) / max(tr.duration(), 1e-9), 2)
    else:
        futures = []
        period = 1.0 / open_rate
        t_start = time.monotonic()
        n_open = int(open_rate * open_seconds)
        for i in range(n_open):
            slot = t_start + i * period
            delay = slot - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            x = reqs[i % len(reqs)]
            futures.append((time.monotonic(), batcher.submit(x)))
        for _, fut in futures:
            fut.result(60)
        open_dt = time.monotonic() - t_start
        # each future stamps its own resolution time — submit->resolve
        # is the true per-request latency even though collection is
        # serial
        lat_open = [fut._t_resolved - t_sub for t_sub, fut in futures]

    if record_trace:
        rec = tr if tr is not None else _at.Trace(
            "serve",
            [{"t": round(i / open_rate, 6),
              "rows": int(reqs[i % len(reqs)].shape[0])}
             for i in range(n_open)],
            {"dim": dim, "rate": open_rate}, seed=0)
        rec.save(record_trace)

    lat_closed.sort()
    lat_open.sort()
    out = {
        "metric": "serve_load",
        "value": round(closed_n / closed_dt, 2),
        "unit": "requests/sec",
        "model": {"hidden": hidden, "dim": dim,
                  "buckets": list(pred.ladder.batches)},
        "tuning": (pred.tuning or {}).get("config"),
        "quantization": ({"mode": pred.quantization["mode"],
                          "calib_sha": pred.quantization["calib_sha"],
                          "covered": pred.quantization["covered"],
                          "total": pred.quantization["total"]}
                         if pred.quantization else None),
        "trace": tr.summary() if tr is not None else None,
        "warm_compile_seconds": round(warm_s, 3),
        "programs_compiled": compiles_after_warm,
        "request_path_compiles": pred.compile_count - compiles_after_warm,
        "closed_loop": {
            "threads": closed_threads,
            "requests": closed_n,
            "throughput_rps": round(closed_n / closed_dt, 2),
            "p50_ms": round(_percentile(lat_closed, 50) * 1e3, 3),
            "p99_ms": round(_percentile(lat_closed, 99) * 1e3, 3),
        },
        "open_loop": {
            "offered_rps": open_rate,
            "requests": n_open,
            "achieved_rps": round(len(lat_open) / open_dt, 2),
            "p50_ms": round(_percentile(lat_open, 50) * 1e3, 3)
            if lat_open else None,
            "p99_ms": round(_percentile(lat_open, 99) * 1e3, 3)
            if lat_open else None,
        },
        "batches": batcher.batch_count,
        "requests": batcher.request_count,
    }
    registry.close()
    print(json.dumps(out))
    return out


def compare_quant_paths(hidden=256, dim=64, classes=16, rungs=(1, 2, 4, 8),
                        threads=6, requests=30):
    """``--compare-quant-paths``: fp32 vs post-training-int8 serving
    A/B on the same model, ladder and traffic — a relative
    measurement, so it ALWAYS runs on CPU (same tunnel rationale as
    --compare-update-paths).  Proves, per rung, from the lowered
    StableHLO via the costs.py per-op table, that the quantized
    program moves >= 2x fewer weight+activation bytes through its
    compute ops (dot/conv); and measures what int8 costs in accuracy
    (max rel err + top-1 agreement vs the fp32 path on identical
    inputs) and buys/costs in latency under identical closed-loop
    traffic.  On CPU the byte reduction is the honest headline — XLA's
    CPU int8 GEMMs are not the MXU path, so wall-clock parity, not
    speedup, is expected (docs/quantization.md).  Asserts zero
    request-path compiles on BOTH paths.  Prints ONE BENCH-schema
    JSON line and returns the dict."""
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import serve, sym
    from mxnet_tpu.observability import costs
    from mxnet_tpu.quantize import calibrate, hlo_has_int8_compute

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="sfc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="sfc2")
    net = sym.softmax(net)
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=(1, dim))
    params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}

    calib = calibrate(
        net, params,
        [rs.randn(4, dim).astype(np.float32) for _ in range(8)],
        name="bench")

    registry = serve.ModelRegistry()
    preds = {}
    try:
        for path, kw in (("fp32", {}),
                         ("int8", {"quantize": "int8", "calib": calib})):
            t0 = time.perf_counter()
            preds[path] = (registry.load(
                "bench-" + path, net, params,
                data_shapes={"data": (1, dim)},
                ladder=serve.BucketLadder(batches=rungs), **kw),
                time.perf_counter() - t0)

        # -- per-rung compute-op byte accounting from the lowered HLO --
        per_rung = {}
        byte_ratios = []
        for b in rungs:
            row = {}
            for path, (pred, _) in preds.items():
                text = pred.lowered_text(pred.rung_shapes(b))
                if path == "int8" and not hlo_has_int8_compute(text):
                    raise RuntimeError(
                        "rung %d of the quantized path lowered with no "
                        "int8 dot/conv" % b)
                row[path] = sum(
                    r["bytes"] for r in costs.parse_hlo_ops(text)
                    if r["op"] in ("dot_general", "dot", "convolution"))
            ratio = row["fp32"] / max(row["int8"], 1.0)
            byte_ratios.append(ratio)
            per_rung[b] = {
                "fp32_compute_bytes": int(row["fp32"]),
                "int8_compute_bytes": int(row["int8"]),
                "byte_reduction_x": round(ratio, 2),
            }

        # -- accuracy on identical inputs at every rung ----------------
        # rel err is gated per rung; top-1 agreement is pooled over
        # every sample (a per-rung min at rung 1 would let a single
        # near-tie argmax flip read as 0% agreement)
        worst_err = 0.0
        agree, total = 0, 0
        for b in list(rungs) + [max(rungs)] * 16:
            x = rs.randn(b, dim).astype(np.float32)
            ref = preds["fp32"][0].predict(x)[0].asnumpy()
            out = preds["int8"][0].predict(x)[0].asnumpy()
            worst_err = max(worst_err, float(
                np.abs(out - ref).max() / max(np.abs(ref).max(), 1e-20)))
            agree += int((out.argmax(-1) == ref.argmax(-1)).sum())
            total += ref.shape[0]
        worst_top1 = agree / total

        # -- identical closed-loop traffic through both batchers -------
        reqs = [rs.randn(rs.randint(1, 5), dim).astype(np.float32)
                for _ in range(64)]
        perf = {}
        for path, (pred, warm_s) in preds.items():
            batcher = registry.batcher("bench-" + path, max_wait_ms=1.0)
            warm = pred.compile_count
            lats, errors = [], []
            lock = threading.Lock()

            def worker(tid, batcher=batcher, lats=lats, errors=errors,
                       lock=lock):
                mine = []
                try:
                    for i in range(requests):
                        x = reqs[(tid * requests + i) % len(reqs)]
                        t0 = time.monotonic()
                        batcher.submit(x).result(60)
                        mine.append(time.monotonic() - t0)
                except Exception as exc:
                    with lock:
                        errors.append("worker %d: %r" % (tid, exc))
                finally:
                    with lock:
                        lats.extend(mine)

            ths = [threading.Thread(target=worker, args=(t,))
                   for t in range(threads)]
            t0 = time.monotonic()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            dt = time.monotonic() - t0
            if errors:
                raise RuntimeError("quant A/B %s loop failed: %s"
                                   % (path, "; ".join(errors[:3])))
            lats.sort()
            perf[path] = {
                "warm_compile_seconds": round(warm_s, 3),
                "throughput_rps": round(threads * requests / dt, 2),
                "p50_ms": round(_percentile(lats, 50) * 1e3, 3),
                "p99_ms": round(_percentile(lats, 99) * 1e3, 3),
                "request_path_compiles": pred.compile_count - warm,
            }
        qreport = preds["int8"][0].quantization
    finally:
        registry.close()

    min_ratio = min(byte_ratios)
    out = {
        "metric": "quant_paths",
        "value": round(min_ratio, 2),
        "unit": "x fewer compute-op bytes (worst rung)",
        "model": {"hidden": hidden, "dim": dim, "classes": classes,
                  "rungs": list(rungs)},
        "quantization": {"mode": qreport["mode"],
                         "calib_sha": qreport["calib_sha"],
                         "covered": qreport["covered"],
                         "total": qreport["total"]},
        "per_rung": per_rung,
        "max_rel_err": round(worst_err, 5),
        "top1_agreement": round(worst_top1, 4),
        "fp32": perf["fp32"],
        "int8": perf["int8"],
        "quant_ok": (min_ratio >= 2.0 and worst_err <= 0.1
                     and worst_top1 >= 0.95
                     and perf["fp32"]["request_path_compiles"] == 0
                     and perf["int8"]["request_path_compiles"] == 0),
    }
    print(json.dumps(out))
    return out


def serve_fleet_bench(hidden=64, dim=16, classes=8, open_rate=60.0,
                      open_seconds=2.0, replicas=3, pool=16):
    """``--serve-fleet``: open-loop load through the multi-replica
    serving fleet's router at 1 vs N replicas — REAL replica
    processes (mxnet_tpu.serve.replica) sharing one persistent XLA
    compile cache, so replicas 2..N warm from disk.  Per-request
    latency is measured from the request's SCHEDULED arrival slot
    (queue wait included — no coordinated omission).  Prints ONE
    BENCH-schema JSON line with per-stage p50/p99 + throughput and
    asserts zero request-path compiles on every replica."""
    import queue as _queue
    import tempfile
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import model as model_mod, serve, sym

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="ffc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="ffc2")
    net = sym.softmax(net)
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = net.infer_shape(data=(1, dim))
    params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    prefix = os.path.join(tmp, "m")
    model_mod.save_checkpoint(prefix, 1, net, params, {})
    spec = [{"name": "m", "prefix": prefix, "epoch": 1,
             "data_shapes": {"data": [1, dim]},
             "batches": [1, 2, 4, 8]}]
    reqs = [rs.randn(rs.randint(1, 5), dim).astype(np.float32)
            for _ in range(64)]

    def run_stage(fleet, n_replicas):
        compiles_before = {k: fleet.stats(k)["compile_count"]
                           for k in fleet.keys()}
        n = int(open_rate * open_seconds)
        slots = _queue.Queue()
        t_start = time.monotonic() + 0.2
        for i in range(n):
            slots.put((t_start + i / open_rate, i))
        lat = []
        errors = []
        lock = threading.Lock()

        def worker():
            while True:
                try:
                    slot, i = slots.get_nowait()
                except _queue.Empty:
                    return
                delay = slot - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                try:
                    fleet.router.predict("m",
                                         {"data": reqs[i % len(reqs)]})
                except Exception as exc:
                    with lock:
                        errors.append(repr(exc))
                    return
                with lock:
                    # latency from the SCHEDULED arrival: a backed-up
                    # fleet pays its queue wait here instead of
                    # silently slowing the offered rate
                    lat.append(time.monotonic() - slot)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(pool)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.monotonic() - t0
        if errors:
            raise RuntimeError("fleet bench stage failed: %s"
                               % "; ".join(errors[:3]))
        request_path = 0
        for k in fleet.keys():
            if fleet.stats(k)["compile_count"] != \
                    compiles_before.get(k, {}):
                request_path += 1
        lat.sort()
        return {
            "replicas": n_replicas,
            "offered_rps": open_rate,
            "requests": len(lat),
            "achieved_rps": round(len(lat) / dt, 2),
            "p50_ms": round(_percentile(lat, 50) * 1e3, 3),
            "p99_ms": round(_percentile(lat, 99) * 1e3, 3),
            "request_path_compiles": request_path,
        }

    fleet = serve.Fleet(spec, replicas=1, workdir=tmp, max_wait_ms=1.0,
                        router_kwargs={"probe_interval": 0.2})
    try:
        t0 = time.monotonic()
        fleet.start()
        first_up = time.monotonic() - t0
        stage1 = run_stage(fleet, 1)
        t0 = time.monotonic()
        for _ in range(replicas - 1):
            fleet._spawn()
        fleet.wait_routable(count=replicas)
        scale_out = time.monotonic() - t0
        stageN = run_stage(fleet, replicas)
        cache_entries = len(os.listdir(fleet.compile_cache_dir))
    finally:
        fleet.stop()
    request_path = stage1["request_path_compiles"] + \
        stageN["request_path_compiles"]
    out = {
        "metric": "serve_fleet",
        "value": stageN["achieved_rps"],
        "unit": "requests/sec",
        "model": {"hidden": hidden, "dim": dim},
        "first_replica_up_seconds": round(first_up, 2),
        "scale_out_seconds": round(scale_out, 2),
        "compile_cache_entries": cache_entries,
        "request_path_compiles": request_path,
        "stages": [stage1, stageN],
    }
    print(json.dumps(out))
    if request_path:
        raise RuntimeError(
            "fleet bench: %d replica(s) compiled in the request path"
            % request_path)
    return out


def _decode_toy(vocab=48, dim=24, seed=0):
    from mxnet_tpu.test_utils import tiny_attention_lm
    return tiny_attention_lm(vocab=vocab, dim=dim, seed=seed)


def compare_decode_paths(sessions=16, prompt_len=16, new_tokens=32,
                         block_size=8, vocab=48, dim=16):
    """``--compare-decode-paths``: batched decode ticks (paged pool,
    one dispatch serves every session's next token) vs SERIAL
    per-session dense decode (the PR-9 DecodeSession discipline: one
    dense worst-case cache and one dispatch per session per token).
    Both paths run the SAME step function and their token streams are
    checked bit-equal, so the speedup is pure dispatch/batching, not
    a different model.  Prints ONE BENCH-schema JSON line with
    aggregate tokens/sec for both paths and the speedup."""
    import warnings

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.serve.decode import DecodeBatcher, DecodeEngine

    params, step_fn, prefill_fn, token_spec, input_spec = _decode_toy(
        vocab=vocab, dim=dim)
    max_len = prompt_len + new_tokens + 1
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, vocab, size=prompt_len).astype(np.int32)
               for _ in range(sessions)]

    # -- serial baseline: one dense program, per-session caches, one
    # dispatch per session per token (prompt fed token by token — the
    # dense path has no prefill program) -------------------------------
    padded_len = -(-max_len // block_size) * block_size
    dense = jax.jit(step_fn)
    cache_zero = {"k": jnp.zeros((1, padded_len, dim), jnp.float32),
                  "v": jnp.zeros((1, padded_len, dim), jnp.float32)}
    lowered = dense.lower(
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            cache_zero),
        {"tok": jax.ShapeDtypeStruct((1,), jnp.int32)},
        jax.ShapeDtypeStruct((1,), jnp.int32))
    dense_prog = lowered.compile()
    del lowered

    def serial_decode(prompt):
        cache = dict(cache_zero)
        stream = []
        cur = None
        t = 0
        for tok in prompt:
            out, cache = dense_prog(
                params, cache, {"tok": np.asarray([tok], np.int32)},
                np.asarray([t], np.int32))
            t += 1
            cur = int(np.asarray(out)[0])   # d2h readback per token
        for _ in range(new_tokens):
            stream.append(cur)
            if len(stream) >= new_tokens:
                break
            out, cache = dense_prog(
                params, cache, {"tok": np.asarray([cur], np.int32)},
                np.asarray([t], np.int32))
            t += 1
            cur = int(np.asarray(out)[0])
        return stream

    t0 = time.monotonic()
    serial_streams = [serial_decode(p) for p in prompts]
    serial_dt = time.monotonic() - t0
    total_tokens = sessions * new_tokens
    serial_tps = total_tokens / serial_dt

    # -- batched ticks over the paged pool ------------------------------
    rungs = [1]
    while rungs[-1] < sessions:
        rungs.append(rungs[-1] * 2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")     # CPU ignores donation
        engine = DecodeEngine(
            step_fn, prefill_fn, token_spec, input_spec, params=params,
            max_len=max_len, block_size=block_size,
            num_blocks=sessions * (-(-max_len // block_size)) + 2,
            session_rungs=rungs, donate=True, label="bench")
        warm_compiles = engine.compile_count
        batcher = DecodeBatcher(engine, max_wait_ms=1.0)
        t0 = time.monotonic()
        sess = [batcher.start({"tok": p}, max_new_tokens=new_tokens)
                for p in prompts]
        batched_streams = [[int(o) for o in s.result(120)]
                           for s in sess]
        batched_dt = time.monotonic() - t0
        request_path_compiles = engine.compile_count - warm_compiles
        ticks = batcher.tick_count
        batcher.close()
        engine.close()
    batched_tps = total_tokens / batched_dt

    if batched_streams != serial_streams:
        raise RuntimeError(
            "decode bench: batched token streams are not bit-equal "
            "to the serial dense decode — the comparison is void")

    speedup = batched_tps / serial_tps
    out = {
        "metric": "serve_decode_compare",
        "value": round(speedup, 3),
        "unit": "x_tokens_per_sec",
        "sessions": sessions,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "total_tokens": total_tokens,
        "serial_tokens_per_sec": round(serial_tps, 2),
        "batched_tokens_per_sec": round(batched_tps, 2),
        "serial_seconds": round(serial_dt, 4),
        "batched_seconds": round(batched_dt, 4),
        "decode_ticks": ticks,
        "request_path_compiles": request_path_compiles,
        "streams_bit_equal": True,
        # the acceptance bar: batched ticks must at least double the
        # aggregate token throughput at >= 8 concurrent sessions
        "speedup_ok": speedup >= 2.0 and request_path_compiles == 0,
    }
    print(json.dumps(out))
    return out


def serve_decode_bench(rate=12.0, seconds=3.0, prompt_lo=4,
                       prompt_hi=24, new_tokens=24, vocab=48, dim=24,
                       block_size=8, record_trace=None, trace=None):
    """``--serve-decode``: open-loop many-session decode load — new
    sessions arrive on a fixed schedule (no coordinated omission: the
    arrival grid never waits for the system), each decodes
    *new_tokens* greedily through the continuous-batching tick loop.
    Per-token latencies come from the batcher's delivery stamps (each
    token is stamped when its tick resolves, not when the client gets
    scheduled).  Prints ONE BENCH-schema JSON line with p50/p99 token
    latency, p50/p99 time-to-first-token, aggregate tokens/sec and
    request_path_compiles.

    ``--record-trace PATH`` serializes the session-arrival schedule
    (prompt lengths + offsets) as an autotune trace; ``--trace PATH``
    replays one instead of the synthetic grid.  A tuned-store entry
    for model "bench-open" (workload decode) overrides the
    hand-picked block size / session rungs / tick window."""
    import warnings

    from mxnet_tpu.autotune import trace as _at
    from mxnet_tpu.autotune.store import lookup as _at_lookup
    from mxnet_tpu.serve.decode import DecodeBatcher, DecodeEngine

    tr = None
    if trace is not None:
        tr = _at.Trace.load(trace)
        if tr.kind != "decode":
            raise ValueError("bench --serve-decode needs a decode "
                             "trace, got kind=%r" % tr.kind)
        vocab = int(tr.meta.get("vocab", vocab))
        new_tokens = int(tr.meta.get("new_tokens", new_tokens))
        prompts = tr.payloads()
        prompt_hi = max(p.shape[0] for p in prompts)
        n_sessions = len(prompts)
        rate = round((n_sessions - 1) / max(tr.duration(), 1e-9), 2)
    else:
        n_sessions = int(rate * seconds)
        rs = np.random.RandomState(5)
        prompts = [rs.randint(0, vocab,
                              size=rs.randint(prompt_lo,
                                              prompt_hi + 1))
                   .astype(np.int32) for _ in range(n_sessions)]
    if record_trace:
        rec = tr if tr is not None else _at.Trace(
            "decode",
            [{"t": round(i / rate, 6), "prompt_len": int(p.shape[0])}
             for i, p in enumerate(prompts)],
            {"vocab": vocab, "new_tokens": new_tokens, "rate": rate},
            seed=5)
        rec.save(record_trace)

    params, step_fn, prefill_fn, token_spec, input_spec = _decode_toy(
        vocab=vocab, dim=dim)
    max_len = prompt_hi + new_tokens + 1
    # tuned-store pickup (docs/autotuning.md): an entry for
    # ("bench-open", decode) replaces the hand-picked knobs
    tuned = _at_lookup("bench-open", "decode")
    tcfg = (tuned or {}).get("config") or {}
    if tuned:
        block_size = int(tcfg.get("MXNET_SERVE_KV_BLOCK_SIZE")
                         or block_size)
    session_rungs = tuple(tcfg.get("ladder") or (1, 2, 4, 8, 16, 32))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        engine = DecodeEngine(
            step_fn, prefill_fn, token_spec, input_spec, params=params,
            max_len=max_len, block_size=block_size,
            num_blocks=n_sessions * (-(-max_len // block_size)) + 2,
            session_rungs=session_rungs, donate=True,
            label="bench-open")
        warm_compiles = engine.compile_count
        batcher = DecodeBatcher(
            engine, max_wait_ms=None if tuned else 1.0)

        shed_box = [0]

        def _start(prompt, _i):
            try:
                return batcher.start({"tok": prompt},
                                     max_new_tokens=new_tokens)
            except Exception:
                shed_box[0] += 1
                return None

        t_start = time.monotonic()
        if tr is not None:
            records, _replay_wall = _at.replay(tr, _start)
        else:
            period = 1.0 / rate
            records = []
            for i in range(n_sessions):
                slot = t_start + i * period
                delay = slot - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                t_sub = time.monotonic()
                records.append((i * period, t_sub,
                                _start(prompts[i], i)))
        arrivals = [(t_sub, s) for _slot, t_sub, s in records
                    if s is not None]
        shed = shed_box[0]
        for _, s in arrivals:
            s.result(120)
        wall = time.monotonic() - t_start
        request_path_compiles = engine.compile_count - warm_compiles
        ticks = batcher.tick_count
        batcher.close()
        engine.close()

    ttft, token_lat = [], []
    total_tokens = 0
    for t_sub, s in arrivals:
        stamps = s.stamps()
        total_tokens += len(stamps)
        if not stamps:
            continue
        ttft.append(stamps[0] - t_sub)
        token_lat.append(stamps[0] - t_sub)
        token_lat.extend(b - a for a, b in zip(stamps, stamps[1:]))
    token_lat.sort()
    ttft.sort()
    out = {
        "metric": "serve_decode_load",
        "value": round(total_tokens / wall, 2),
        "unit": "tokens/sec",
        "offered_sessions_per_sec": rate,
        "sessions": len(arrivals),
        "sessions_shed": shed,
        "new_tokens": new_tokens,
        "total_tokens": total_tokens,
        "decode_ticks": ticks,
        "token_p50_ms": round(_percentile(token_lat, 50) * 1e3, 3)
        if token_lat else None,
        "token_p99_ms": round(_percentile(token_lat, 99) * 1e3, 3)
        if token_lat else None,
        "ttft_p50_ms": round(_percentile(ttft, 50) * 1e3, 3)
        if ttft else None,
        "ttft_p99_ms": round(_percentile(ttft, 99) * 1e3, 3)
        if ttft else None,
        "request_path_compiles": request_path_compiles,
        "tuning": tcfg or None,
        "trace": tr.summary() if tr is not None else None,
    }
    print(json.dumps(out))
    return out


def serve_decode_failover_bench(streams=6, new_tokens=48, replicas=2,
                                vocab=32, dim=16, seed=5, kill_at=30,
                                block_size=4, max_len=64):
    """``--serve-decode --failover``: the decode fault-tolerance path
    measured, not just gated — N wire decode streams through the
    fleet router while one replica is armed to hard-kill mid-run
    (``replica_kill_decode_at``), so the streams it was serving fail
    over to a survivor and resume from the router journal.
    Consumers stamp every delivered token client-side.  Prints ONE
    BENCH-schema JSON line: resume latency p50/p99 out of
    ``DecodeStream.resume_stamps`` (kill detection → resumed and
    serving), steady vs dip tokens/sec (best vs worst interior 50 ms
    delivery window — the dip is what the kill costs the fleet), full
    bit-equality of every stream to the solo dense decode, and
    request_path_compiles=0 on the survivors."""
    import tempfile
    import threading

    from mxnet_tpu import serve
    from mxnet_tpu.test_utils import (dense_decode_reference,
                                      tiny_attention_lm)

    prompt = np.array([3, 1, 2], dtype=np.int32)
    blocks_per = -(-max_len // block_size)
    spec = [{"name": "lm", "kind": "decode_lm", "vocab": vocab,
             "dim": dim, "seed": seed, "dtype": "float32",
             "max_len": max_len, "block_size": block_size,
             "num_blocks": streams * blocks_per + 8,
             "rungs": [1, 2, 4]}]
    dparams, dstep, _, _, _ = tiny_attention_lm(vocab=vocab, dim=dim,
                                                seed=seed)
    ref = dense_decode_reference(dparams, dstep, list(prompt),
                                 new_tokens, max_len, dim)

    tmp = tempfile.mkdtemp(prefix="bench_decode_fo_")
    fleet = serve.Fleet(spec, replicas=replicas, workdir=tmp,
                        max_wait_ms=1.0,
                        router_kwargs={"probe_interval": 0.2,
                                       "retries": 4})
    stamps = []                       # (t_mono, stream_seq) per token
    errors = []
    lock = threading.Lock()

    def consume(s):
        while True:
            try:
                s.next_output(timeout=120)
            except StopIteration:
                return
            except Exception as exc:
                with lock:
                    errors.append("stream %d: %r" % (s.seq, exc))
                return
            with lock:
                stamps.append((time.monotonic(), s.seq))

    try:
        fleet.start()
        armed = fleet.replace(fleet.keys()[0], extra_env={
            "MXNET_CHAOS": "replica_kill_decode_at=%d" % kill_at})
        fleet.wait_routable(count=replicas, model="lm")
        survivors = [k for k in fleet.keys() if k != armed]
        warm = {k: fleet.stats(k)["decode"]["lm"]["compile_count"]
                for k in survivors}
        t0 = time.monotonic()
        opened = [fleet.router.decode_open("lm", {"tok": prompt},
                                           max_new_tokens=new_tokens)
                  for _ in range(streams)]
        threads = [threading.Thread(target=consume, args=(s,),
                                    daemon=True) for s in opened]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        wall = time.monotonic() - t0
        rec = fleet.record(armed)
        deadline = time.monotonic() + 30
        while rec["proc"].poll() is None and \
                time.monotonic() < deadline:
            time.sleep(0.1)
        kill_rc = rec["proc"].poll()
        bit_equal = True
        for s in opened:
            got = [int(np.asarray(t)) for t in s.tokens()]
            if got != ref:
                bit_equal = False
                errors.append("stream %d not bit-equal" % s.seq)
        moved = [s for s in opened if s.failover_count >= 1]
        resume_lat = sorted(b - a for s in moved
                            for a, b in s.resume_stamps)
        request_path = sum(
            fleet.stats(k)["decode"]["lm"]["compile_count"] - warm[k]
            for k in survivors)
        for s in opened:
            s.close()
    finally:
        fleet.stop()

    # interior 50 ms delivery windows: steady = best, dip = worst —
    # the first/last windows are ramp and tail, not the kill's cost
    win = 0.05
    rates = []
    if stamps:
        times = sorted(t for t, _ in stamps)
        t_lo, t_hi = times[0], times[-1]
        n_win = max(1, int((t_hi - t_lo) / win))
        counts = [0] * n_win
        for t in times:
            counts[min(n_win - 1, int((t - t_lo) / win))] += 1
        rates = [c / win for c in counts[1:-1]] or \
            [c / win for c in counts]
    total_tokens = len(stamps)
    out = {
        "metric": "serve_decode_failover",
        "value": round(resume_lat[-1] * 1e3, 3) if resume_lat
        else None,
        "unit": "ms_worst_resume",
        "streams": streams,
        "new_tokens": new_tokens,
        "replicas": replicas,
        "total_tokens": total_tokens,
        "tokens_per_sec": round(total_tokens / wall, 2),
        "failed_over_streams": len(moved),
        "resumes": len(resume_lat),
        "resume_p50_ms": round(
            _percentile(resume_lat, 50) * 1e3, 3)
        if resume_lat else None,
        "resume_p99_ms": round(
            _percentile(resume_lat, 99) * 1e3, 3)
        if resume_lat else None,
        "tokens_per_sec_steady": round(max(rates), 2)
        if rates else None,
        "tokens_per_sec_dip": round(min(rates), 2) if rates else None,
        "dip_ratio": round(min(rates) / max(rates), 3)
        if rates and max(rates) else None,
        "bit_equal": bit_equal,
        "kill_rc": kill_rc,
        "request_path_compiles": request_path,
        "errors": errors or None,
    }
    print(json.dumps(out))
    if errors or not moved or kill_rc != 137 or request_path:
        raise RuntimeError(
            "decode failover bench failed: moved=%d rc=%r "
            "request_path_compiles=%d errors=%s"
            % (len(moved), kill_rc, request_path, errors[:3]))
    return out


def decompose_main():
    """``--decompose``: lower the north-star train step, attribute its
    cost per op against probed roofline peaks, print the human table
    to stderr and ONE JSON line (BENCH schema: metric=mfu_decompose)
    to stdout.  Runs on whatever platform ``_ensure_platform``
    selects — CPU (BENCH_ALLOW_CPU=1) uses a small config, so CI can
    smoke the whole decompose path in seconds."""
    _ensure_platform()
    import jax
    from mxnet_tpu.observability import costs as _costs

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    batch = 128 if on_tpu else 8
    image = 224 if on_tpu else 32
    peak = _probe_peak_flops() if on_tpu else \
        _probe_peak_flops(iters=8, n=1024)
    bw = _probe_peak_bw() if on_tpu else _probe_peak_bw(mb=32)
    r = timed_resnet_train(
        batch, image, remat=None, iters=4 if on_tpu else 2,
        scan_n=2, warmup=1, optimizer="lbsgd" if on_tpu else "sgd",
        multi_precision=on_tpu)
    if not r.get("hlo_text"):
        print("bench: could not lower the train step for decompose",
              file=sys.stderr)
        return 1
    table = _costs.cost_table(text=r["hlo_text"], peak_flops=peak,
                              peak_bytes_s=bw, top=20)
    print(_costs.format_table(table, limit=24), file=sys.stderr)
    out = {
        "metric": "mfu_decompose",
        "batch_size": batch,
        "image_size": image,
        "device": getattr(dev, "device_kind", str(dev)),
        "peak_flops_probe": peak,
        "peak_bw_probe": bw,
        "machine_balance": table["machine_balance"],
        "total_flops": table["total_flops"],
        "total_bytes": table["total_bytes"],
        "flops_vs_xla": table.get("flops_vs_xla"),
        "ms_per_step": round(r["dt"] / r["iters"] * 1e3, 2),
        "rows": table["rows"],
    }
    print(json.dumps(out))
    return 0


def audit_main():
    """``--audit``: lower the graftir representative AOT program set,
    run rules GI001-GI005, diff per-program flops/bytes/sha against
    the committed manifest, print the human diff table to stderr and
    ONE JSON line (BENCH schema: metric=ir_audit) to stdout.  A
    static measurement over lowered text — nothing executes, so it
    ALWAYS runs on CPU (the committed manifest shas are CPU lowers)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from tools.graftir import (audit_programs, diff as manifest_diff,
                               format_diff_table, load as manifest_load,
                               DEFAULT_MANIFEST)
    from tools.graftir.programs import build_representative_set

    programs = build_representative_set()
    engine, findings = audit_programs(programs)
    rows, violations = manifest_diff(programs,
                                     manifest_load(DEFAULT_MANIFEST))
    print(format_diff_table(rows), file=sys.stderr)
    for v in violations:
        print("bench: audit: %s" % v, file=sys.stderr)
    out = {
        "metric": "ir_audit",
        "programs": len(programs),
        "findings": len(findings),
        "new_findings": engine.stats["new"],
        "violations": len(violations),
        "flops_total": round(sum(r["flops"] or 0.0 for r in rows), 1),
        "bytes_total": round(sum(r["bytes"] or 0.0 for r in rows), 1),
        "rows": rows,
    }
    print(json.dumps(out))
    return 1 if (engine.stats["new"] or violations) else 0


def _argv_path(flag):
    """Value of ``flag PATH`` in sys.argv, or None (bench's dispatch
    is flag-sniffing, not argparse — keep trace flags the same)."""
    if flag not in sys.argv:
        return None
    i = sys.argv.index(flag)
    if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
        raise SystemExit("bench: %s needs a path" % flag)
    return sys.argv[i + 1]


def main():
    if "--serve" in sys.argv:
        # serving load test: throughput + latency of the compiled
        # inference subsystem under concurrent traffic.  Platform
        # rules match the training bench (_ensure_platform): a TPU
        # target is health-probed, CPU needs BENCH_ALLOW_CPU=1.
        _ensure_platform()
        serve_bench(record_trace=_argv_path("--record-trace"),
                    trace=_argv_path("--trace"),
                    quantize=_argv_path("--quantize"))
        return
    if "--compare-quant-paths" in sys.argv:
        # fp32 vs post-training-int8 serving on the same ladder and
        # traffic — a relative measurement (HLO byte accounting +
        # accuracy + latency deltas), so it ALWAYS runs on CPU (same
        # tunnel rationale as --compare-update-paths)
        os.environ["JAX_PLATFORMS"] = "cpu"
        out = compare_quant_paths()
        if not out["quant_ok"]:
            print("bench: quantized path failed the bar (%.2fx fewer "
                  "compute-op bytes at the worst rung, rel err %.4f, "
                  "top-1 %.3f, request_path_compiles fp32=%d int8=%d "
                  "— want >= 2x, <= 0.1, >= 0.95, 0, 0)"
                  % (out["value"], out["max_rel_err"],
                     out["top1_agreement"],
                     out["fp32"]["request_path_compiles"],
                     out["int8"]["request_path_compiles"]),
                  file=sys.stderr)
            return 1
        return 0
    if "--decompose" in sys.argv:
        return decompose_main()
    if "--audit" in sys.argv:
        return audit_main()
    if "--compare-decode-paths" in sys.argv:
        # batched decode ticks vs serial per-session dense decode — a
        # relative dispatch-count measurement, so it ALWAYS runs on
        # CPU (same tunnel rationale as --compare-update-paths)
        os.environ["JAX_PLATFORMS"] = "cpu"
        out = compare_decode_paths()
        if not out["speedup_ok"]:
            print("bench: batched decode failed the bar (%.2fx "
                  "tokens/sec vs serial at %d sessions, "
                  "request_path_compiles=%d — want >= 2x with 0)"
                  % (out["value"], out["sessions"],
                     out["request_path_compiles"]), file=sys.stderr)
            return 1
        return 0
    if "--serve-decode" in sys.argv:
        # open-loop many-session continuous-batching decode load;
        # latency distribution + aggregate tokens/sec.  --failover
        # instead measures the fault-tolerance path: resume latency
        # and the tokens/sec dip around a seeded mid-run replica kill
        _ensure_platform()
        if "--failover" in sys.argv:
            serve_decode_failover_bench()
            return
        serve_decode_bench(record_trace=_argv_path("--record-trace"),
                           trace=_argv_path("--trace"))
        return
    if "--serve-fleet" in sys.argv:
        # open-loop load through the multi-replica fleet router at
        # 1 vs N replica processes (request_path_compiles=0 asserted)
        _ensure_platform()
        serve_fleet_bench()
        return
    if "--compare-input-paths" in sys.argv:
        # serial vs device-prefetched input path — a host/device
        # overlap measurement, so it ALWAYS runs on CPU (same tunnel
        # rationale as --compare-update-paths below)
        os.environ["JAX_PLATFORMS"] = "cpu"
        out = compare_input_paths()
        if not out["overlap_ok"]:
            print("bench: input pipelining failed the overlap bar "
                  "(pipelined %.2f ms/step vs serial %.2f — want "
                  "< 0.7x)" % (out["pipelined_ms_per_step"],
                               out["serial_ms_per_step"]),
                  file=sys.stderr)
            return 1
        return 0
    if "--compare-update-paths" in sys.argv:
        # explicit A/B of the two update paths — a relative dispatch-
        # overhead measurement, so it ALWAYS runs on CPU: the shell's
        # JAX_PLATFORMS=axon export would route it over the TPU tunnel
        # with none of the tunnel-health probing below (a wedged tunnel
        # hangs compute forever)
        os.environ["JAX_PLATFORMS"] = "cpu"
        compare_update_paths()
        return
    _ensure_platform()
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    batch = 128 if on_tpu else 8
    image = 224 if on_tpu else 32
    warmup, iters = (4, 20) if on_tpu else (2, 10)
    # 10-deep scan: at ~50 ms/step one dispatch covers ~500 ms, taking
    # the 4-7 ms tunnel latency under 1.5% of the window (CPU keeps a
    # short scan — it multiplies compile time)
    scan_n = 10 if on_tpu else 2

    # input-stall accounting across the timed window: share of wall
    # time the step loop spent blocked on the input pipeline
    # (input_wait_seconds histogram — 0.0 here because the bench feeds
    # a device-resident batch, the pipelined ideal the real input path
    # is measured against via --compare-input-paths)
    from mxnet_tpu.observability import metrics as _obs_metrics
    _wait_hist = _obs_metrics.REGISTRY.get("input_wait_seconds")
    _wait0 = _wait_hist.sum if _wait_hist is not None else 0.0

    r = timed_resnet_train(
        batch, image,
        # BENCH_REMAT=dots|full selects a jax.checkpoint policy for the
        # step (HBM-pressure experiments on hardware)
        remat=os.environ.get("BENCH_REMAT") or None,
        iters=iters, scan_n=scan_n, warmup=warmup,
        optimizer="lbsgd" if on_tpu else "sgd",
        multi_precision=on_tpu)
    img_s, dt, iters = r["img_s"], r["dt"], r["iters"]
    flops, final_loss = r["flops_per_step"], r["final_loss"]
    input_stall_share = round(
        ((_wait_hist.sum - _wait0) if _wait_hist is not None else 0.0)
        / dt, 4)

    peak_probe = _probe_peak_flops() if on_tpu else None
    sustained = flops * iters / dt
    mfu = sustained / peak_probe if peak_probe else None
    mfu_error = None
    if mfu is not None and not 0.0 < mfu <= 1.0:
        # a broken probe (half-recovered tunnel, wedged clock) must
        # not crash the WHOLE bench run and lose the throughput
        # number with it: record mfu=null + a structured warning and
        # keep going (the round artifact stays parseable)
        mfu_error = (
            "MFU %.4f outside (0, 1] — measurement or probe is broken "
            "(sustained %.1f TF/s, probe %.1f TF/s)"
            % (mfu, sustained / 1e12, peak_probe / 1e12))
        print("bench: " + mfu_error, file=sys.stderr)
        from mxnet_tpu.observability import events as _obs_events
        _obs_events.emit("warning", kind="mfu_probe_broken",
                         mfu=round(mfu, 4), sustained_flops=sustained,
                         peak_flops_probe=peak_probe)
        mfu = None

    # per-op cost attribution of the exact step just timed (rows name
    # the op a round-over-round MFU regression blames; see
    # docs/observability.md and bench --decompose for the full table)
    decompose = None
    if r.get("hlo_text"):
        try:
            from mxnet_tpu.observability import costs as _costs
            peak_bw = _probe_peak_bw() if on_tpu else None
            table = _costs.cost_table(text=r["hlo_text"],
                                      peak_flops=peak_probe,
                                      peak_bytes_s=peak_bw, top=12)
            decompose = {
                "machine_balance": table["machine_balance"],
                "total_flops": table["total_flops"],
                "total_bytes": table["total_bytes"],
                "rows": table["rows"],
            }
        except Exception as e:
            print("bench: decompose failed (%r)" % e, file=sys.stderr)

    out = {
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "peak_flops_probe": peak_probe,
        "peak_flops_datasheet": _datasheet_peak(dev),
        "sustained_flops": sustained,
        "batch_size": batch,
        "image_size": image,
        "device": getattr(dev, "device_kind", str(dev)),
        "flops_per_step": flops,
        "final_loss": final_loss,
        "mfu_error": mfu_error,
        "input_stall_share": input_stall_share,
        "decompose": decompose,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
