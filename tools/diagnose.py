#!/usr/bin/env python
"""Environment diagnosis report (reference: tools/diagnose.py — the
"attach this to your bug report" dump: platform, python, deps, build
info, connectivity).  Offline build: no network checks; instead reports
the pieces that matter here — jax/XLA backends, device inventory,
native library builds, and key env knobs.

    python tools/diagnose.py
"""

from __future__ import annotations

import os
import platform
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def section(title):
    print("----------%s----------" % title)


def main():
    section("Platform")
    print("system   :", platform.platform())
    print("machine  :", platform.machine())
    print("processor:", platform.processor() or "n/a")
    print("cpus     :", os.cpu_count())

    section("Python")
    print("version :", sys.version.replace("\n", " "))
    print("prefix  :", sys.prefix)

    section("Dependencies")
    for mod in ("numpy", "jax", "jaxlib", "cv2", "google.protobuf"):
        try:
            m = __import__(mod)
            ver = getattr(m, "__version__", "unknown")
            print("%-16s %s" % (mod, ver))
        except ImportError as e:
            print("%-16s MISSING (%s)" % (mod, e))

    section("Framework")
    try:
        import mxnet_tpu as mx
        from mxnet_tpu.ops.registry import list_ops
        print("mxnet_tpu:", os.path.dirname(mx.__file__))
        print("operators:", len(list_ops()))
    except Exception as e:
        print("import failed:", e)

    section("Devices")
    print("JAX_PLATFORMS:", os.environ.get("JAX_PLATFORMS", "<unset>"))
    try:
        import jax
        print("default backend:", jax.default_backend())
        for d in jax.devices():
            print("  ", d, getattr(d, "device_kind", ""))
    except Exception as e:
        # a wedged TPU tunnel can hang device discovery; report rather
        # than hang (run under timeout(1) if the tunnel is suspect)
        print("device discovery failed:", e)

    section("Native builds")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for lib in ("libmxtpu_predict.so", "libmxtpu_nd.so",
                "librecordio_reader.so"):
        path = os.path.join(root, "build", lib)
        print("%-22s %s" % (lib, "built" if os.path.exists(path)
                            else "not built (make -C src/capi src/io)"))

    section("Environment knobs")
    try:
        from mxnet_tpu import config
        for name in config.list_env():
            print("%-40s %r" % (name, config.get_env(name)))
    except Exception:
        for k, v in sorted(os.environ.items()):
            if k.startswith(("MXNET_", "DMLC_", "XLA_", "JAX_")):
                print("%-40s %r" % (k, v))
    return 0


if __name__ == "__main__":
    sys.exit(main())
