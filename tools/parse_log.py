#!/usr/bin/env python
"""Summarize training logs into a table (reference: tools/parse_log.py
— epoch/accuracy/speed extraction into markdown or csv).

Reads the logging output our fit loops produce (Speedometer lines like
``Epoch[3] Batch [40]  Speed: 123.4 samples/sec  accuracy=0.91`` and
epoch summaries like ``Epoch[3] Validation-accuracy=0.87`` /
``Epoch[3] Time cost=12.3``) and prints one row per epoch.

    python tools/parse_log.py train.log [--format markdown|csv]
"""

from __future__ import annotations

import argparse
import re
import sys


_SPEED = re.compile(r"Epoch\[(\d+)\].*Speed:\s*([\d.]+)")
_TRAIN = re.compile(r"Epoch\[(\d+)\].*?Train-([\w-]+)=([\d.naninf]+)")
_VAL = re.compile(r"Epoch\[(\d+)\].*?Validation-([\w-]+)=([\d.naninf]+)")
_TIME = re.compile(r"Epoch\[(\d+)\].*?Time cost=([\d.]+)")


def parse(lines):
    """-> {epoch: {"speed": [..], "train-x": v, "val-x": v, "time": v}}"""
    table = {}

    def row(epoch):
        return table.setdefault(int(epoch), {"speed": []})

    for line in lines:
        m = _SPEED.search(line)
        if m:
            row(m.group(1))["speed"].append(float(m.group(2)))
        for pat, prefix in ((_TRAIN, "train-"), (_VAL, "val-")):
            m = pat.search(line)
            if m:
                row(m.group(1))[prefix + m.group(2)] = float(m.group(3))
        m = _TIME.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
    return table


def render(table, fmt="markdown"):
    metrics = sorted({k for r in table.values() for k in r
                      if k not in ("speed",)})
    header = ["epoch", "speed(avg)"] + metrics
    rows = []
    for epoch in sorted(table):
        r = table[epoch]
        speed = (sum(r["speed"]) / len(r["speed"])) if r["speed"] else ""
        vals = [str(epoch),
                "%.1f" % speed if speed != "" else ""]
        vals += ["%g" % r[m] if m in r else "" for m in metrics]
        rows.append(vals)
    if fmt == "csv":
        return "\n".join(",".join(r) for r in [header] + rows)
    out = ["| " + " | ".join(header) + " |",
           "|" + "---|" * len(header)]
    out += ["| " + " | ".join(r) + " |" for r in rows]
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logfile")
    ap.add_argument("--format", default="markdown",
                    choices=("markdown", "csv"))
    args = ap.parse_args(argv)
    with open(args.logfile) as f:
        print(render(parse(f), args.format))
    return 0


if __name__ == "__main__":
    sys.exit(main())
