#!/usr/bin/env python
"""Localhost cluster launcher for distributed kvstore jobs.

Reference: ``tools/launch.py`` (delegates to the dmlc-core local tracker,
``tools/launch.py:28-50``), which spawns scheduler + server + worker
processes on one host with ``DMLC_ROLE`` environment variables
(``tests/nightly/test_all.sh:55,98`` uses ``-n 4 --launcher local``).

TPU-native differences: there is no separate scheduler role — the first
server process binds the root port and doubles as the rendezvous point —
and worker ranks are assigned directly by this script.

Usage:
    python tools/launch.py -n 2 python examples/train_mnist.py \
        --kv-store dist_sync
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job on localhost "
                    "(reference: tools/launch.py --launcher local)")
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=1,
                        help="number of server processes; server i "
                             "listens on root port + i and keys are "
                             "sharded across servers by stable hash "
                             "(reference: PSKV, kvstore_dist.h:161-169)")
    parser.add_argument("--launcher", default="local",
                        choices=["local"],
                        help="only the local (single-host multi-process) "
                             "launcher is implemented")
    parser.add_argument("--port", type=int, default=None,
                        help="root port (default: pick a free one)")
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VALUE env for all roles")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command to run per worker")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.num_servers < 0:
        parser.error("-s/--num-servers must be >= 0 (0 = no parameter "
                     "servers: a pure jax.distributed worker group, "
                     "parallel.multihost)")
    command = args.command
    if command[0] == "--":
        command = command[1:]

    port = args.port or _free_port()
    base_env = dict(os.environ)
    for kv in args.env:
        k, _, v = kv.partition("=")
        base_env[k] = v
    base_env.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })

    procs = []
    try:
        servers = []
        for i in range(args.num_servers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "server"
            env["DMLC_SERVER_ID"] = str(i)
            servers.append(("server%d" % i, subprocess.Popen(
                command, env=env)))
        procs.extend(servers)
        if servers:
            time.sleep(0.3)  # let the root server bind first
        workers = []
        for i in range(args.num_workers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "worker"
            env["DMLC_WORKER_RANK"] = str(i)
            env["DMLC_WORKER_ID"] = str(i)
            p = subprocess.Popen(command, env=env)
            workers.append(("worker%d" % i, p))
        procs.extend(workers)

        rc = 0
        pending = dict(workers)
        while pending:
            for name, p in list(pending.items()):
                r = p.poll()
                if r is None:
                    continue
                del pending[name]
                if r != 0:
                    print("launch.py: %s exited with code %d" % (name, r),
                          file=sys.stderr)
                    rc = rc or r
            for name, p in servers:
                r = p.poll()
                if r is not None and r != 0:
                    # a dead server deadlocks every worker; fail fast
                    print("launch.py: %s died with code %d — aborting"
                          % (name, r), file=sys.stderr)
                    return r
            time.sleep(0.2)
        return rc
    finally:
        for name, p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for name, p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
