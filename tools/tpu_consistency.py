#!/usr/bin/env python
"""Cross-backend consistency sweep on real hardware (reference:
tests/python/gpu/test_operator_gpu.py reusing the CPU suite through
check_consistency, test_utils.py:1207 — "the single most important
harness to reproduce", SURVEY §4.1).

Runs a library of small symbols through ``test_utils.check_consistency``
comparing the TPU backend against CPU — outputs AND gradients must agree
within per-dtype tolerance.  Requires a healthy TPU; run:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/tpu_consistency.py

Exits nonzero listing any mismatching case.
"""

from __future__ import annotations

import os
import sys
import traceback

# The tunnel deployment pins JAX_PLATFORMS to the TPU plugin only
# (e.g. "axon"); the sweep needs the host backend too, so append it
# BEFORE jax first initializes.  The accelerator stays first in the
# priority list and remains the default platform.
_plat = os.environ.get("JAX_PLATFORMS", "")
if _plat and "cpu" not in _plat.replace(" ", "").split(","):
    os.environ["JAX_PLATFORMS"] = _plat + ",cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as _np


def _cases(mx):
    """(name, symbol, shapes, tolerances) — one per op family."""
    s = mx.sym
    d = s.var("data")
    w = s.var("w")
    cases = []

    def add(name, sym, shapes, rtol=2e-3, atol=2e-3, grad_req="write",
            location=None):
        cases.append((name, sym, shapes, rtol, atol, grad_req, location))

    add("fc_relu", s.Activation(s.FullyConnected(
        d, num_hidden=16, name="fc"), act_type="relu"),
        {"data": (4, 8)})
    add("conv_bn_pool", s.Pooling(s.Activation(s.Convolution(
        d, num_filter=8, kernel=(3, 3), pad=(1, 1), name="c"),
        act_type="relu"), kernel=(2, 2), stride=(2, 2),
        pool_type="max"), {"data": (2, 3, 8, 8)})
    add("softmax_ce", s.SoftmaxOutput(s.FullyConnected(
        d, num_hidden=5, name="f2"), s.var("lbl")),
        {"data": (6, 10), "lbl": (6,)})
    add("layernorm", s.LayerNorm(d, s.var("g"), s.var("b")),
        {"data": (4, 12), "g": (12,), "b": (12,)})
    add("batch_dot", s.batch_dot(d, w),
        {"data": (3, 4, 5), "w": (3, 5, 6)})
    add("broadcast_chain", s.broadcast_mul(
        s.broadcast_add(d, w), s.exp(-d)),
        {"data": (4, 6), "w": (1, 6)})
    add("reduce_stack", s.sum(s.square(d), axis=1),
        {"data": (5, 7)})
    add("transpose_reshape", s.Reshape(s.transpose(d, (0, 2, 1)),
                                       (0, -1)),
        {"data": (2, 3, 4)})
    add("take_embed", s.Embedding(s.var("idx"), w, input_dim=20,
                                  output_dim=6),
        {"idx": (3, 4), "w": (20, 6)})
    add("rnn_tanh", s.RNN(d, s.var("p"), s.var("st"),
                          state_size=8, num_layers=1, mode="rnn_tanh",
                          name="r"),
        {"data": (5, 2, 4), "p": (8 * (4 + 8 + 2),), "st": (1, 2, 8)})
    add("attention", s.contrib.DotProductAttention(
        s.var("q"), s.var("k"), s.var("v")),
        {"q": (1, 2, 16, 8), "k": (1, 2, 16, 8), "v": (1, 2, 16, 8)})

    # --- round 4: one case per remaining op family ---------------------
    # recurrent: multi-layer bidirectional LSTM / GRU
    add("lstm_bidir", s.RNN(d, s.var("pl"), s.var("sl"), s.var("cl"),
                            state_size=6, num_layers=2, mode="lstm",
                            bidirectional=True, name="rl"),
        {"data": (4, 2, 5)})
    add("gru", s.RNN(d, s.var("pg"), s.var("sg"), state_size=6,
                     num_layers=1, mode="gru", name="rg"),
        {"data": (4, 2, 5)})
    # dense NN long tail
    add("deconv", s.Deconvolution(d, num_filter=4, kernel=(2, 2),
                                  stride=(2, 2), name="dc"),
        {"data": (2, 3, 5, 5)})
    add("pool_avg_global", s.Pooling(d, global_pool=True,
                                     pool_type="avg", kernel=(1, 1)),
        {"data": (2, 4, 6, 6)})
    add("dropout_eval", s.Dropout(d, p=0.5), {"data": (4, 6)})
    add("lrn", s.LRN(d, nsize=3), {"data": (2, 4, 5, 5)})
    add("svm_output", s.SVMOutput(s.FullyConnected(
        d, num_hidden=4, name="f3"), s.var("lbl2")),
        {"data": (5, 6), "lbl2": (5,)})
    # detection / spatial
    add("roi_align", s.contrib.ROIAlign(
        d, s.var("rois"), pooled_size=(2, 2), spatial_scale=1.0),
        {"data": (1, 3, 8, 8), "rois": (2, 5)})
    add("bilinear_sampler", s.BilinearSampler(d, s.var("grid")),
        {"data": (1, 2, 6, 6), "grid": (1, 2, 4, 4)})
    add("spatial_transformer", s.SpatialTransformer(
        d, s.FullyConnected(s.var("loc"), num_hidden=6, name="lf"),
        target_shape=(4, 4), transform_type="affine",
        sampler_type="bilinear"),
        {"data": (1, 2, 6, 6), "loc": (1, 8)})
    # forward-only families (integer / index outputs)
    add("box_nms", s.contrib.box_nms(d, overlap_thresh=0.5),
        {"data": (1, 6, 6)}, grad_req="null")
    add("topk_argsort", s.topk(d, k=3, ret_typ="indices"),
        {"data": (4, 9)}, grad_req="null")
    add("bipartite_match", s.contrib.bipartite_matching(
        d, threshold=1e-12), {"data": (5, 4)}, grad_req="null")
    add("quantize_int8", s.contrib.quantize(
        d, s.var("qmin"), s.var("qmax"), out_type="int8"),
        {"data": (3, 7), "qmin": (1,), "qmax": (1,)}, grad_req="null",
        location={"qmin": _np.array([-3.0], _np.float32),
                  "qmax": _np.array([3.0], _np.float32)})
    # graph-level sparse ops (explicit integer row ids)
    add("sparse_square_sum", s._square_sum(s._sparse_retain(
        d, s.var("sridx")), axis=1),
        {"data": (6, 5), "sridx": (3,)}, grad_req="null",
        location={"sridx": _np.array([0, 2, 5], _np.float32)})
    add("sparse_dot_dense", s.dot(s.cast_storage(d, stype="default"), w),
        {"data": (4, 6), "w": (6, 3)})
    # flash vs chunked vs oracle attention agree ON the device itself
    add("attention_causal", s.contrib.DotProductAttention(
        s.var("q"), s.var("k"), s.var("v"), causal=True),
        {"q": (1, 2, 32, 8), "k": (1, 2, 32, 8), "v": (1, 2, 32, 8)})

    # --- session-2 additions: remaining op families ---------------------
    add("conv_depthwise", s.Convolution(
        d, num_filter=6, kernel=(3, 3), pad=(1, 1), num_group=6,
        name="dwc"), {"data": (2, 6, 8, 8)})
    add("conv_dilated", s.Convolution(
        d, num_filter=4, kernel=(3, 3), pad=(2, 2), dilate=(2, 2),
        name="dlc"), {"data": (1, 3, 9, 9)})
    add("embedding_take", s.take(w, s.var("idx2")),
        {"w": (10, 5), "idx2": (4,)}, grad_req="null",
        location={"idx2": _np.array([1, 3, 5, 7], _np.float32)})
    add("linalg_chain", s.linalg_gemm2(d, w),
        {"data": (3, 4), "w": (4, 5)})
    add("l2norm_channel", s.L2Normalization(d, mode="channel"),
        {"data": (2, 4, 5, 5)})
    add("adaptive_avg_pool", s.contrib.AdaptiveAvgPooling2D(
        d, output_size=(3, 3)), {"data": (2, 3, 7, 7)})
    add("bilinear_resize", s.contrib.BilinearResize2D(
        d, height=9, width=9), {"data": (1, 2, 5, 5)})
    add("instance_norm", s.InstanceNorm(d, s.var("g2"), s.var("b2")),
        {"data": (2, 3, 6, 6), "g2": (3,), "b2": (3,)})
    add("smooth_l1_where", s.smooth_l1(
        s.where(s.var("c") > 0, d, -d), scalar=1.0),
        {"data": (4, 5), "c": (4, 5)})
    add("foreach_scan", s.contrib.foreach(
        lambda x_, st: (x_ * st[0], [st[0] + 1.0]),
        d, [s.var("st0")])[0],
        {"data": (5, 3, 4), "st0": (3, 4)})
    add("stem_s2d", s.space_to_depth(d, block_size=2),
        {"data": (2, 4, 6, 6)})
    add("multibox_prior_det", s.concat(
        s.Reshape(s.MultiBoxPrior(d, sizes=(0.3,), ratios=(1.0, 2.0)),
                  (1, -1, 4)), dim=1),
        {"data": (1, 3, 4, 4)}, grad_req="null")

    # --- round-5 additions ----------------------------------------------
    # CTC with per-sequence lengths (flag-gated optional graph inputs)
    add("ctc_lengths", s.CTCLoss(
        d, s.var("clab"), s.var("cdl"), s.var("cll"),
        use_data_lengths=True, use_label_lengths=True,
        blank_label="last"),
        {"data": (6, 2, 5), "clab": (2, 3), "cdl": (2,), "cll": (2,)},
        grad_req="null",
        location={"clab": _np.array([[1, 2, 0], [3, 1, 2]], _np.float32),
                  "cdl": _np.array([4, 6], _np.float32),
                  "cll": _np.array([2, 3], _np.float32)})
    # 'full'-convention pooling (the SSD/VGG pool3 path)
    add("pool_full_conv", s.Pooling(
        d, kernel=(2, 2), stride=(2, 2), pool_type="max",
        pooling_convention="full"), {"data": (1, 2, 7, 7)})
    # GShard-einsum MoE (routing argmax ties break identically only at
    # matched precision — exactly what the sweep checks)
    add("moe_ffn", s.MoEFFN(d, s.var("mgw"), s.var("mw1"),
                            s.var("mw2"), capacity_factor=2.0),
        {"data": (16, 8), "mgw": (8, 4), "mw1": (4, 8, 16),
         "mw2": (4, 16, 8)})
    return cases


def run_cases(only=None):
    """Run cases inline in THIS process (child mode)."""
    import mxnet_tpu as mx
    from mxnet_tpu import test_utils

    backends = test_utils.list_backends()
    print("backends:", backends)
    if "tpu" not in backends:
        print("no TPU backend available — nothing to compare")
        return 2
    if "cpu" not in backends:
        print("no CPU backend available — cannot compare (JAX_PLATFORMS"
              " must include cpu alongside the accelerator)")
        return 2

    failures = []
    cases = _cases(mx)
    if only:
        known = {c[0] for c in cases}
        unknown = [n for n in only if n not in known]
        if unknown:
            print("unknown case name(s): %s\navailable: %s"
                  % (unknown, sorted(known)))
            return 2
    n_run = 0
    for name, sym, shapes, rtol, atol, grad_req, location in cases:
        if only and name not in only:
            continue
        n_run += 1
        try:
            # complete the shape dict (weights etc.) via inference
            arg_shapes, _, _ = sym.infer_shape(**shapes)
            full = dict(zip(sym.list_arguments(), arg_shapes))
            test_utils.check_consistency(
                sym, shapes=full, location=location,
                backends=["cpu", "tpu"], rtol=rtol, atol=atol,
                grad_req=grad_req)
            print("OK   %s" % name, flush=True)
        except Exception:
            failures.append(name)
            print("FAIL %s\n%s" % (name, traceback.format_exc()),
                  flush=True)
    print("%d/%d consistent" % (n_run - len(failures), n_run))
    return 1 if failures or not n_run else 0


def _spawn_abandonable(argv, deadline_s, inactivity_s=None):
    """Run argv, streaming stdout; ABANDON (never reap) on deadline.

    A child stuck in a wedged TPU driver call sits in uninterruptible
    sleep: SIGKILL doesn't reap it and waiting blocks forever
    (bench.py's guard, docs/PERF_NOTES.md).  Returns (rc | None, out).

    ``inactivity_s`` resets the clock whenever the child produces
    output — a batch child running N cases gets ``inactivity_s`` per
    case instead of one fixed budget for the whole batch.
    """
    import subprocess
    import time
    # binary pipe: a non-blocking read on a text-mode wrapper raises
    # TypeError when no data is buffered; raw read returns None safely
    p = subprocess.Popen(argv, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT)
    os.set_blocking(p.stdout.fileno(), False)
    out = []

    def _drain():
        chunk = p.stdout.read()
        if chunk:
            text = chunk.decode("utf-8", "replace")
            sys.stdout.write(text)
            sys.stdout.flush()
            out.append(text)
            return True
        return False

    end = time.time() + deadline_s
    while time.time() < end:
        if _drain() and inactivity_s is not None:
            end = time.time() + inactivity_s
        if p.poll() is not None:
            _drain()
            return p.returncode, "".join(out)
        time.sleep(0.5)
    try:
        p.kill()
    except Exception:
        pass
    return None, "".join(out)


def _probe_healthy(deadline_s=150):
    # bench.py owns the canonical abandoned-child probe; reuse it
    import bench
    return bench._probe_tpu_once(deadline_s)


def _journal_path():
    return os.environ.get(
        "CONSISTENCY_JOURNAL",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "results", "tpu_r4",
            "consistency_results.txt"))


def _read_journal():
    """Case name -> last recorded status (OK/FAIL/HANG/SKIP)."""
    done = {}
    try:
        with open(_journal_path()) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2 and parts[0] in (
                        "OK", "FAIL", "HANG", "SKIP"):
                    done[parts[1]] = parts[0]
    except OSError:
        pass
    return done


def _log_journal(status, name):
    import time as _t
    path = _journal_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write("%s %s %s\n" % (
                status, name, _t.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          _t.gmtime())))
    except OSError:
        pass


def main():
    argv = sys.argv[1:]
    if argv and argv[0] == "--child":
        return run_cases(argv[1:] or None)

    # Parent mode: ONE abandonable child runs the whole pending batch
    # (a fresh process per case pays a full JAX init + tunnel compile
    # each, ~2 min/case); the inactivity deadline gives each case its
    # own hang budget.  On a hang the current case is recorded, tunnel
    # health is probed, and a new child resumes after it.  Every case
    # result is appended to the journal so an interrupted sweep resumes
    # where it stopped (CONSISTENCY_FRESH=1 ignores the journal).
    import mxnet_tpu as mx
    only = [a for a in argv if not a.startswith("-")] or None
    names = [c[0] for c in _cases(mx)]
    if only:
        unknown = [n for n in only if n not in names]
        if unknown:
            print("unknown case name(s): %s\navailable: %s"
                  % (unknown, sorted(names)))
            return 2
        names = [n for n in names if n in only]

    prior = {} if os.environ.get("CONSISTENCY_FRESH") else _read_journal()
    ok = fail = 0
    pending = []
    for n in names:
        if prior.get(n) == "OK":
            print("OK   %s (journaled)" % n, flush=True)
            ok += 1
        else:
            pending.append(n)

    per_case_s = float(os.environ.get("CONSISTENCY_CASE_DEADLINE", 600))
    zero_progress_crashes = 0
    while pending:
        rc, out = _spawn_abandonable(
            [sys.executable, os.path.abspath(__file__), "--child"]
            + pending, per_case_s, inactivity_s=per_case_s)
        if rc == 2 and "backend available" in out:
            # missing cpu/tpu backend: every case would fail the same
            # way — keep the documented fast exit 2 (nothing to compare)
            return 2
        finished = set()
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 2 and parts[0] in ("OK", "FAIL"):
                name = parts[1]
                if name in pending:
                    finished.add(name)
                    _log_journal(parts[0], name)
                    if parts[0] == "OK":
                        ok += 1
                    else:
                        fail += 1
        pending = [n for n in pending if n not in finished]
        if not pending:
            break
        if rc is not None:
            if rc == 0:
                # clean exit with cases unreported should not happen
                # (the child runs every requested case) — don't loop
                for n in pending:
                    print("FAIL %s (child rc=0 with no verdict)" % n,
                          flush=True)
                    _log_journal("FAIL", n)
                    fail += 1
                break
            # child crashed mid-sweep: blame only the FIRST unfinished
            # case (the one it was running) and respawn for the rest —
            # one bad case must not eat the remaining hardware window.
            # But repeated crashes with ZERO cases completed mean the
            # environment (not a case) is broken: stop journaling false
            # per-case FAILs and abort so the journal stays resumable.
            zero_progress_crashes = (0 if finished
                                     else zero_progress_crashes + 1)
            if zero_progress_crashes >= 3:
                print("ABORT: %d consecutive child crashes with no "
                      "case verdicts — environment failure, %d cases "
                      "left un-run" % (zero_progress_crashes,
                                       len(pending)), flush=True)
                fail += len(pending)
                pending = []
                continue
            crashed = pending.pop(0)
            print("FAIL %s (child crashed rc=%s)" % (crashed, rc),
                  flush=True)
            _log_journal("FAIL", crashed)
            fail += 1
            continue
        # hang: the first unfinished case wedged its computation
        hung = pending.pop(0)
        print("HANG %s (abandoned after %ds inactivity)"
              % (hung, per_case_s), flush=True)
        _log_journal("HANG", hung)
        fail += 1
        if pending and not _probe_healthy():
            for n in pending:
                print("SKIP %s (tunnel wedged)" % n, flush=True)
                _log_journal("SKIP", n)
            fail += len(pending)
            pending = []
    print("%d/%d consistent" % (ok, ok + fail))
    return 1 if fail or not ok else 0


if __name__ == "__main__":
    sys.exit(main())
