#!/usr/bin/env python
"""Cross-backend consistency sweep on real hardware (reference:
tests/python/gpu/test_operator_gpu.py reusing the CPU suite through
check_consistency, test_utils.py:1207 — "the single most important
harness to reproduce", SURVEY §4.1).

Runs a library of small symbols through ``test_utils.check_consistency``
comparing the TPU backend against CPU — outputs AND gradients must agree
within per-dtype tolerance.  Requires a healthy TPU; run:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/tpu_consistency.py

Exits nonzero listing any mismatching case.
"""

from __future__ import annotations

import sys
import traceback


def _cases(mx):
    """(name, symbol, shapes, tolerances) — one per op family."""
    s = mx.sym
    d = s.var("data")
    w = s.var("w")
    cases = []

    def add(name, sym, shapes, rtol=2e-3, atol=2e-3):
        cases.append((name, sym, shapes, rtol, atol))

    add("fc_relu", s.Activation(s.FullyConnected(
        d, num_hidden=16, name="fc"), act_type="relu"),
        {"data": (4, 8)})
    add("conv_bn_pool", s.Pooling(s.Activation(s.Convolution(
        d, num_filter=8, kernel=(3, 3), pad=(1, 1), name="c"),
        act_type="relu"), kernel=(2, 2), stride=(2, 2),
        pool_type="max"), {"data": (2, 3, 8, 8)})
    add("softmax_ce", s.SoftmaxOutput(s.FullyConnected(
        d, num_hidden=5, name="f2"), s.var("lbl")),
        {"data": (6, 10), "lbl": (6,)})
    add("layernorm", s.LayerNorm(d, s.var("g"), s.var("b")),
        {"data": (4, 12), "g": (12,), "b": (12,)})
    add("batch_dot", s.batch_dot(d, w),
        {"data": (3, 4, 5), "w": (3, 5, 6)})
    add("broadcast_chain", s.broadcast_mul(
        s.broadcast_add(d, w), s.exp(-d)),
        {"data": (4, 6), "w": (1, 6)})
    add("reduce_stack", s.sum(s.square(d), axis=1),
        {"data": (5, 7)})
    add("transpose_reshape", s.Reshape(s.transpose(d, (0, 2, 1)),
                                       (0, -1)),
        {"data": (2, 3, 4)})
    add("take_embed", s.Embedding(s.var("idx"), w, input_dim=20,
                                  output_dim=6),
        {"idx": (3, 4), "w": (20, 6)})
    add("rnn_tanh", s.RNN(d, s.var("p"), s.var("st"),
                          state_size=8, num_layers=1, mode="rnn_tanh",
                          name="r"),
        {"data": (5, 2, 4), "p": (8 * (4 + 8 + 2),), "st": (1, 2, 8)})
    add("attention", s.contrib.DotProductAttention(
        s.var("q"), s.var("k"), s.var("v")),
        {"q": (1, 2, 16, 8), "k": (1, 2, 16, 8), "v": (1, 2, 16, 8)})
    return cases


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import test_utils

    backends = test_utils.list_backends()
    print("backends:", backends)
    if "tpu" not in backends:
        print("no TPU backend available — nothing to compare")
        return 2

    failures = []
    cases = _cases(mx)
    for name, sym, shapes, rtol, atol in cases:
        try:
            # complete the shape dict (weights etc.) via inference
            arg_shapes, _, _ = sym.infer_shape(**shapes)
            full = dict(zip(sym.list_arguments(), arg_shapes))
            test_utils.check_consistency(
                sym, shapes=full, backends=["cpu", "tpu"],
                rtol=rtol, atol=atol)
            print("OK   %s" % name, flush=True)
        except Exception:
            failures.append(name)
            print("FAIL %s\n%s" % (name, traceback.format_exc()),
                  flush=True)
    print("%d/%d consistent" % (len(cases) - len(failures), len(cases)))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
