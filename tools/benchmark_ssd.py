#!/usr/bin/env python
"""SSD-300 (VGG16-reduced) inference throughput — the mirror of the
reference's `example/ssd/benchmark_score.py` (detection headline).

The full graph — backbone, multi-scale heads, 8732 anchors, box decode
+ NMS (`MultiBoxDetection`) — is ONE XLA program timed with the shared
scanned-forward discipline.

    PYTHONPATH=/root/repo:/root/.axon_site python tools/benchmark_ssd.py \
        [--batches 1 32] [--classes 20]

Run only with a healthy tunnel and NO other TPU process.  On CPU
(JAX_PLATFORMS=cpu) shrinks shapes for a plumbing smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, os.path.join(os.path.dirname(_HERE), "examples"))


def timed_ssd(batch, image, classes, iters, scan_n, warmup=1,
              dtype="bfloat16"):
    import jax.numpy as jnp
    from mxnet_tpu.executor import _build_eval
    import bench
    from ssd_model import build_ssd300_infer

    net = build_ssd300_infer(num_classes=classes)
    arg_shapes, _, _ = net.infer_shape(data0=(batch, 3, image, image))
    shapes = dict(zip(net.list_arguments(), arg_shapes))
    rng = np.random.RandomState(0)
    cdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    params = {n: jnp.asarray(
        rng.randn(*s).astype(np.float32) * 0.05).astype(cdt)
        for n, s in shapes.items() if n != "data0"}
    xd = jnp.asarray(rng.randn(batch, 3, image, image)
                     .astype(np.float32)).astype(cdt)
    eval_fn = _build_eval(net, False)
    dt, n, _ = bench.timed_scan_forward(eval_fn, params, {}, xd, {},
                                        scan_n, iters, warmup)
    return batch * n / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", nargs="*", type=int, default=[1, 32])
    ap.add_argument("--classes", type=int, default=20)
    ap.add_argument("--image", type=int, default=300)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    args = ap.parse_args()

    import mxnet_tpu  # noqa: F401  (re-pins jax platform from env)
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        # image must stay 300: smaller inputs collapse the last
        # feature scales (3x3 valid convs) to zero size
        args.batches, args.iters = [1], 4

    for batch in args.batches:
        try:
            img_s = timed_ssd(batch, args.image, args.classes,
                              args.iters, scan_n=5 if on_tpu else 2,
                              dtype=args.dtype)
            print(json.dumps({
                "metric": "ssd300_vgg16_infer", "batch": batch,
                "image": args.image, "classes": args.classes,
                "dtype": args.dtype, "img_s": round(img_s, 2),
                "device": "tpu" if on_tpu else "cpu",
            }), flush=True)
        except Exception as e:
            print(json.dumps({"batch": batch,
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
