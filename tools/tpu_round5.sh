#!/bin/bash
# Round-5 hardware watcher: camp on the tunnel and run every TPU-gated
# deliverable to completion, riding out outages.
#
#   bash tools/tpu_round5.sh            # camp + run everything once
#
# Differences from tools/tpu_round4.sh (one-shot session):
#  * outer loop — if the tunnel is down (or dies mid-step) we sleep and
#    re-probe instead of aborting; a step that already passed (rc=0)
#    leaves a .ok stamp and is skipped on the next pass, so a pass after
#    an outage only redoes the unfinished tail;
#  * the consistency sweep resumes via its per-case journal either way;
#  * a lockfile guards against a second concurrent TPU process (two
#    wedge the tunnel — docs/PERF_NOTES.md);
#  * an overall deadline (default 10 h) so the watcher never collides
#    with the driver's end-of-round bench run.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="$REPO/results/tpu_r5"
LOCK="/tmp/mxtpu_hw.lock"
DEADLINE=$(( $(date +%s) + ${TPU_R5_BUDGET_S:-36000} ))
mkdir -p "$OUT"
export PYTHONPATH="$REPO:/root/.axon_site"
export CONSISTENCY_JOURNAL="$OUT/consistency_results.txt"
# seed the resume journal with cases already proven on TPU in round 4
if [ ! -f "$CONSISTENCY_JOURNAL" ] && [ -f "$REPO/results/tpu_r4/consistency_results.txt" ]; then
  grep '^OK ' "$REPO/results/tpu_r4/consistency_results.txt" > "$CONSISTENCY_JOURNAL"
fi
cd "$REPO"

# bench.py owns the canonical abandoned-child tunnel probe; importing
# bench has no side effects by design (see bench._ensure_platform)
probe() {
  python -c 'import sys, bench; sys.exit(0 if bench._probe_tpu_once(240) else 1)'
}

# acquire the single-TPU-process lock or die: stale locks (dead pid)
# are broken, live ones are honored
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK" 2>/dev/null)" 2>/dev/null; then
  echo "another TPU session holds $LOCK (pid $(cat "$LOCK")); refusing to start"
  exit 3
fi
echo $$ > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

step() {
  name="$1"; shift
  [ -f "$OUT/$name.ok" ] && return 0
  # never START a step past the deadline: the per-step timeouts sum to
  # ~8.5 h, so a pass beginning late must not hold the TPU against the
  # driver's end-of-round bench
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$name skipped (deadline) $(date -u +%FT%TZ)" >> "$OUT/status.txt"
    return 1
  fi
  RUN="$(date -u +%m%dT%H%M%S)"
  echo "=== $name: started $(date -u +%H:%M:%S), log $name.$RUN.log"
  "$@" > "$OUT/$name.$RUN.log" 2>&1
  rc=$?
  echo "=== $name: rc=$rc"
  echo "$name rc=$rc run=$RUN $(date -u +%FT%TZ)" >> "$OUT/status.txt"
  cp "$OUT/$name.$RUN.log" "$OUT/$name.log" 2>/dev/null
  [ $rc -eq 0 ] && touch "$OUT/$name.ok"
  return $rc
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if ! probe; then
    echo "$(date -u +%FT%TZ) tunnel unhealthy; sleeping 300" | tee -a "$OUT/status.txt"
    sleep 300
    continue
  fi
  echo "$(date -u +%FT%TZ) tunnel healthy; starting pass" | tee -a "$OUT/status.txt"

  # a fallback headline number FIRST: a short healthy window must not
  # end with zero bench evidence (the driver's end-of-round bench may
  # meet a dead tunnel again)
  step bench_early timeout 5400 python bench.py
  if [ -f "$OUT/bench_early.ok" ] && [ ! -f "$OUT/bench.json" ]; then
    tail -1 "$OUT/bench_early.log" > "$OUT/bench.json" 2>/dev/null
  fi

  step consistency timeout 5400 python tools/tpu_consistency.py
  step flash       timeout 3600 python tools/flash_sweep.py
  step decompose   timeout 3600 python tools/mfu_sweep.py --decompose
  step score       timeout 3600 python tools/benchmark_score.py
  step score_int8  timeout 1800 python tools/benchmark_score.py \
                     --models resnet50_v1 --batches 32 128 --dtype int8
  step lm          timeout 1800 python tools/benchmark_lm.py
  step lm_long     timeout 1800 python tools/benchmark_lm.py \
                     --seq 8192 --batch 2 --iters 10 --remat dots
  step lm_lstm     timeout 1800 python tools/benchmark_lm.py --arch lstm \
                     --dim 650 --seq 512 --batch 32
  step ssd         timeout 1800 python tools/benchmark_ssd.py
  step bench       timeout 5400 python bench.py
  if [ -f "$OUT/bench.ok" ]; then
    tail -1 "$OUT/bench.log" > "$OUT/bench.json" 2>/dev/null
  fi

  if ls "$OUT"/consistency.ok "$OUT"/flash.ok "$OUT"/bench.ok >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) all core steps complete" | tee -a "$OUT/status.txt"
    break
  fi
  echo "$(date -u +%FT%TZ) pass incomplete; re-probing in 120" | tee -a "$OUT/status.txt"
  sleep 120
done
echo "watcher done; artifacts in $OUT"
tail -12 "$OUT/status.txt"
