#!/usr/bin/env python
"""ResNet-50 training-step MFU sweep on the real chip.

Times bench.py's exact harness (`bench.timed_resnet_train` — same scan
dispatch shape, same readback discipline, same cost-analysis FLOPs)
across batch size x remat policy in ONE process, so a single
healthy-tunnel session answers "which config should bench.py ship?".

    PYTHONPATH=/root/repo:/root/.axon_site python tools/mfu_sweep.py \
        [--configs 128:none 128:dots 256:none 256:dots]

Run only with a healthy tunnel and NO other TPU process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench


def _decompose(peak, batch, iters):
    """Time the step's constituent configurations: fwd-only, then full
    steps with increasing optimizer machinery.  Differences between
    rows locate the non-conv time (PERF_NOTES 'remaining gap' list).
    Ends with the PER-OP cost table of the ship config's lowered step
    (observability.costs): flops, bytes, roofline class, % of step —
    the row an MFU regression blames (ROADMAP item 3)."""
    rows = [
        ("fwd_only", dict(fwd=True)),
        ("sgd_plain_f32", dict(optimizer="sgd", multi_precision=False,
                               momentum=0.0, stem="conv7")),
        ("sgd_mom_mp", dict(optimizer="sgd", multi_precision=True,
                            momentum=0.9, stem="conv7")),
        ("lbsgd_mp_percoparam", dict(optimizer="lbsgd",
                                     multi_precision=True,
                                     coalesce_small=False,
                                     stem="conv7")),
        ("lbsgd_mp_coalesced", dict(optimizer="lbsgd",
                                    multi_precision=True,
                                    coalesce_small=True,
                                    stem="conv7")),
        ("lbsgd_mp_coal_s2d", dict(optimizer="lbsgd",
                                   multi_precision=True,
                                   coalesce_small=True, stem="s2d")),
    ]
    # per-op attribution target: the LAST successful full-step variant
    # (the rows run cheapest->ship config, so later = closer to ship);
    # the emitted JSON names which variant the HLO actually came from
    ship_hlo = None
    ship_variant = None
    for name, kw in rows:
        try:
            if kw.pop("fwd", False):
                r = bench.timed_resnet_fwd(batch, 224, iters=iters,
                                           scan_n=5, warmup=2)
            else:
                r = bench.timed_resnet_train(batch, 224, None,
                                             iters=iters, scan_n=5,
                                             warmup=2, **kw)
                if r.get("hlo_text"):
                    ship_hlo = r["hlo_text"]
                    ship_variant = name
            tf_s = r["flops_per_step"] * r["iters"] / r["dt"] / 1e12
            print(json.dumps({
                "variant": name, "batch": batch,
                "ms_per_step": round(r["dt"] / r["iters"] * 1e3, 2),
                "img_s": round(r["img_s"], 1),
                "tf_s": round(tf_s, 1),
                "mfu": round(tf_s * 1e12 / peak, 4),
            }), flush=True)
        except Exception as e:
            print(json.dumps({"variant": name,
                              "error": repr(e)[:300]}), flush=True)

    if ship_hlo:
        try:
            from mxnet_tpu.observability import costs as _costs
            bw = bench._probe_peak_bw()
            table = _costs.cost_table(text=ship_hlo, peak_flops=peak,
                                      peak_bytes_s=bw, top=20)
            print("per-op attribution (variant=%s)" % ship_variant,
                  file=sys.stderr, flush=True)
            print(_costs.format_table(table, limit=24),
                  file=sys.stderr, flush=True)
            print(json.dumps({
                "per_op": table["rows"],
                "per_op_variant": ship_variant,
                "machine_balance": table["machine_balance"],
                "peak_bw_probe": bw,
                "total_flops": table["total_flops"],
                "total_bytes": table["total_bytes"],
            }), flush=True)
        except Exception as e:
            print(json.dumps({"per_op_error": repr(e)[:300]}),
                  flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="*",
                    default=["128:none", "128:dots", "256:none",
                             "256:dots"],
                    help="batch:remat pairs (remat none|dots|full)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--decompose", action="store_true",
                    help="time fwd-only + optimizer-variant full steps")
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    peak = bench._probe_peak_flops()
    print(json.dumps({"probe_tf_s": round(peak / 1e12, 1)}), flush=True)

    if args.decompose:
        _decompose(peak, args.batch, args.iters)
        return

    for cfg in args.configs:
        bs, _, rm = cfg.partition(":")
        rm = None if rm in ("", "none") else rm
        try:
            r = bench.timed_resnet_train(int(bs), 224, rm,
                                         iters=args.iters, scan_n=5,
                                         warmup=2)
            tf_s = r["flops_per_step"] * r["iters"] / r["dt"] / 1e12
            print(json.dumps({
                "batch": int(bs), "remat": rm or "none",
                "ms_per_step": round(r["dt"] / r["iters"] * 1e3, 2),
                "img_s": round(r["img_s"], 1),
                "tf_s": round(tf_s, 1),
                "mfu": round(tf_s * 1e12 / peak, 4),
                "flops_per_step": r["flops_per_step"],
            }), flush=True)
        except Exception as e:
            print(json.dumps({"batch": bs, "remat": rm or "none",
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
