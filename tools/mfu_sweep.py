#!/usr/bin/env python
"""ResNet-50 training-step MFU sweep on the real chip.

Times the ParallelTrainer step (the bench.py workload) across batch
size x remat policy in ONE process, so a single healthy-tunnel session
answers "which config should bench.py ship?".  Reports ms/step, img/s,
sustained TF/s and MFU against the chained-matmul probe (the bench
denominator, docs/PERF_NOTES.md).

    PYTHONPATH=/root/repo:/root/.axon_site python tools/mfu_sweep.py \
        [--configs 128:none 128:dots 256:none 256:dots]

Timing discipline: steps scanned inside one dispatch, timed to a host
scalar readback (tunnel latency stays out of the number).  Run only
with a healthy tunnel and NO other TPU process.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_config(batch, remat, iters=20, scan_n=5, image=224):
    iters = max(iters, scan_n)  # at least one timed dispatch
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    dev = jax.devices()[0]
    net = vision.get_model("resnet50_v1", classes=1000)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = ParallelTrainer(
        net, loss, optimizer="lbsgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                          "eta": 0.001},
        mesh=make_mesh({"dp": 1}, [dev]), multi_precision=True,
        remat=remat)

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch, 3, image, image).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))
    l = trainer.fit_batch(x, y)
    float(np.asarray(l))

    step = trainer._step_fn

    def multi(params, opt_state, aux, xb, yb, key, lr, t):
        def body(carry, i):
            p, s, a = carry
            p, s, a, l = step(p, s, a, xb, yb,
                              jax.random.fold_in(key, i), lr, t)
            return (p, s, a), l
        (p, s, a), ls = jax.lax.scan(
            body, (params, opt_state, aux), jnp.arange(scan_n))
        return p, s, a, ls[-1]

    multi_j = jax.jit(multi, donate_argnums=(0, 1, 2))
    xd = x._data.astype(jnp.bfloat16)
    yd = y._data
    p, s, a = trainer._params, trainer._opt_state, trainer._aux
    p, s, a, l = multi_j(p, s, a, xd, yd, jax.random.PRNGKey(0),
                         np.float32(0.1), np.int32(1))
    float(np.asarray(l))  # warm

    t0 = time.perf_counter()
    for it in range(iters // scan_n):
        p, s, a, l = multi_j(p, s, a, xd, yd, jax.random.PRNGKey(it + 1),
                             np.float32(0.1), np.int32(1))
    float(np.asarray(l))
    dt = time.perf_counter() - t0
    n = (iters // scan_n) * scan_n

    flops = None
    try:
        ca = step.lower(p, s, a, xd, yd, jax.random.PRNGKey(0),
                        np.float32(0.1), np.int32(1)).compile() \
            .cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if ca and "flops" in ca:
            flops = float(ca["flops"])
    except Exception:
        pass
    if not flops:
        flops = 3 * 4.089e9 * batch
    return {"batch": batch, "remat": remat or "none",
            "ms_per_step": round(dt / n * 1e3, 2),
            "img_s": round(batch * n / dt, 1),
            "tf_s": round(flops * n / dt / 1e12, 1),
            "flops_per_step": flops}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="*",
                    default=["128:none", "128:dots", "256:none",
                             "256:dots"],
                    help="batch:remat pairs (remat none|dots|full)")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import bench
    peak = bench._probe_peak_flops()
    print(json.dumps({"probe_tf_s": round(peak / 1e12, 1)}), flush=True)

    for cfg in args.configs:
        bs, _, rm = cfg.partition(":")
        rm = None if rm in ("", "none") else rm
        try:
            r = run_config(int(bs), rm, iters=args.iters)
            r["mfu"] = round(r["tf_s"] * 1e12 / peak, 4)
            print(json.dumps(r), flush=True)
        except Exception as e:
            print(json.dumps({"batch": bs, "remat": rm or "none",
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
