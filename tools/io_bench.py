"""Input-pipeline throughput benchmark: can the host feed the chip?

Measures ImageRecordIter decode+augment+batch throughput (img/s) at
ImageNet shapes across thread counts, against the training-side demand
(ResNet-50 at ~2,300-3,000 img/s on one chip).  Mirrors the reference's
design point: `src/io/iter_image_recordio_2.cc:141-149` sizes an OMP
decode team for exactly this reason.

Usage:  python tools/io_bench.py [--images 2048] [--threads 1,4,8,16]

Writes one JSON line per config and a summary to stdout; run it on the
bench host and paste the table into docs/PERF_NOTES.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_recfile(path, n, side=512, quality=90):
    """Synthetic ImageNet-ish recordio: n JPEG-encoded random images."""
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rs = np.random.RandomState(0)
    # a small pool of distinct images re-packed n times keeps build time
    # down while every record still pays full JPEG decode cost
    pool = []
    for i in range(32):
        img = (rs.rand(side, side, 3) * 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        pool.append(recordio.pack_img(header, img, quality=quality))
    for i in range(n):
        rec.write_idx(i, pool[i % len(pool)])
    rec.close()


def bench_once(recpath, batch_size, threads, n_images, augment):
    from mxnet_tpu.io import ImageRecordIter
    kwargs = dict(
        path_imgrec=recpath + ".rec", path_imgidx=recpath + ".idx",
        data_shape=(3, 224, 224), batch_size=batch_size,
        preprocess_threads=threads, shuffle=False)
    if augment:
        kwargs.update(rand_crop=True, rand_mirror=True, resize=256,
                      mean_r=123.68, mean_g=116.78, mean_b=103.94,
                      std_r=58.4, std_g=57.1, std_b=57.4)
    else:
        kwargs.update(resize=256)
    it = ImageRecordIter(**kwargs)
    # warm one batch (thread pool spin-up), then time the epoch
    batch = next(iter(it))
    n_seen = batch.data[0].shape[0]
    t0 = time.perf_counter()
    for batch in it:
        n_seen += batch.data[0].shape[0]
        if n_seen >= n_images:
            break
    dt = time.perf_counter() - t0
    return (n_seen - batch_size) / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--threads", default="1,2,4,8,16")
    ap.add_argument("--target", type=float, default=2500.0,
                    help="img/s the chip consumes (ResNet-50 demand)")
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        recpath = os.path.join(td, "synth")
        make_recfile(recpath, max(args.images, 512))
        results = []
        for threads in [int(t) for t in args.threads.split(",")]:
            for augment in (False, True):
                rate = bench_once(recpath, args.batch_size, threads,
                                  args.images, augment)
                row = {"metric": "image_record_iter_throughput",
                       "value": round(rate, 1), "unit": "images/sec",
                       "threads": threads, "augment": augment,
                       "vs_target": round(rate / args.target, 3)}
                results.append(row)
                print(json.dumps(row), flush=True)
    best = max(r["value"] for r in results)
    print(json.dumps({"metric": "image_record_iter_best",
                      "value": best, "unit": "images/sec",
                      "feeds_chip": best >= args.target}))
    return 0 if best >= args.target else 1


if __name__ == "__main__":
    sys.exit(main())
