#!/bin/bash
# Round-4 hardware session: run every TPU-gated deliverable in one
# wedge-safe sequence the moment the tunnel is healthy.
#
#   bash tools/tpu_round4.sh [fast]
#
# Order matters: ONE TPU process at a time (two concurrent wedge the
# tunnel — docs/PERF_NOTES.md), health probe first, generous timeouts,
# artifacts written even on partial completion.  Each step logs to
# results/tpu_r4/<name>.<runid>.log (never overwrites a prior run) and
# appends to status.txt; the consistency sweep journals per-case results
# and resumes where an interrupted run stopped.
#
# "fast" skips the decompose sweep (probe + consistency + flash + bench).

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="$REPO/results/tpu_r4"
RUN="$(date -u +%m%dT%H%M%S)"
mkdir -p "$OUT"
export PYTHONPATH="$REPO:/root/.axon_site"
cd "$REPO"

step() {
  name="$1"; shift
  echo "=== $name: $* (started $(date -u +%H:%M:%S), log $name.$RUN.log)"
  "$@" > "$OUT/$name.$RUN.log" 2>&1
  rc=$?
  echo "=== $name: rc=$rc"
  echo "$name rc=$rc run=$RUN $(date -u +%FT%TZ)" >> "$OUT/status.txt"
  # keep the canonical unsuffixed name pointing at the latest run
  cp "$OUT/$name.$RUN.log" "$OUT/$name.log" 2>/dev/null
  return $rc
}

# 1. health probe (abandonable child, bench.py's guard path)
step probe python -c "
import subprocess, sys, time
p = subprocess.Popen([sys.executable, '-c',
 'import jax, jax.numpy as jnp;'
 'print(int(jnp.sum(jnp.ones((256,256)))))'],
 stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
end = time.time() + 240
while time.time() < end:
    if p.poll() is not None:
        sys.exit(0 if p.returncode == 0 else 1)
    time.sleep(2)
p.kill()
sys.exit(1)
" || { echo "tunnel unhealthy - aborting session"; exit 2; }

# 2. cpu-vs-TPU consistency sweep (VERDICT item 3) — journaled; the
#    committed artifacts are consistency_results.txt + the run log
step consistency timeout 3600 python tools/tpu_consistency.py

# 3. flash fwd+bwd numerics + block sweep (VERDICT item 4)
step flash timeout 3600 python tools/flash_sweep.py

# 4. step decomposition: where does the non-conv time go? (VERDICT 2)
if [ "${1:-}" != "fast" ]; then
  step decompose timeout 3600 python tools/mfu_sweep.py --decompose
fi

# 5. headline benches beyond ResNet: inference score table (fp + int8),
#    transformer + lstm LM, SSD-300 detection
if [ "${1:-}" != "fast" ]; then
  step score timeout 3600 python tools/benchmark_score.py
  step score_int8 timeout 1800 python tools/benchmark_score.py \
      --models resnet50_v1 --batches 32 128 --dtype int8
  step lm timeout 1800 python tools/benchmark_lm.py
  step lm_long timeout 1800 python tools/benchmark_lm.py \
      --seq 8192 --batch 2 --iters 10 --remat dots
  step lm_lstm timeout 1800 python tools/benchmark_lm.py --arch lstm \
      --dim 650 --seq 512 --batch 32
  step ssd timeout 1800 python tools/benchmark_ssd.py
fi

# 6. the round benchmark (VERDICT item 1) — also what the driver runs
step bench timeout 5400 python bench.py
tail -1 "$OUT/bench.$RUN.log" > "$OUT/bench.json" 2>/dev/null

echo "session complete; artifacts in $OUT"
tail -8 "$OUT/status.txt"
