"""Project index + jit-reachability call graph for graftlint.

Builds, without importing anything it analyzes:

* a per-module index of imports, top-level functions, classes/methods
  and nested functions;
* a set of *trace entry points*: functions decorated with
  ``@register_op(...)`` (their array inputs are traced under the eager
  executable cache and the graph executor) and functions passed to
  ``jax.jit`` (as argument or decorator, directly or via
  ``functools.partial``);
* a fixpoint reachability + taint propagation over resolvable call
  edges: a function called (or referenced — ``lax.scan``/``lax.cond``
  style combinators take function *values*) from jit-reachable code is
  jit-reachable, and parameters fed from tainted (possibly-traced)
  names become tainted themselves.

Resolution is deliberately lexical and conservative: bare names via
enclosing scopes -> module top level -> in-project ``from`` imports;
``mod.f`` via import aliases of in-project modules; ``self.f`` /
``cls.f`` via the enclosing class.  Unresolvable calls are skipped —
the baseline absorbs what heuristics miss.
"""

from __future__ import annotations

import ast
import os


def dotted_name(expr):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def literal_int_tuple(node):
    """Statically-known tuple of ints from a Tuple/List/Constant node,
    else None (indeterminate)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


class FunctionInfo:
    """One def (top-level, method, or nested)."""

    __slots__ = ("module", "node", "name", "qualname", "parent",
                 "class_name", "pos_params", "no_default_params",
                 "has_varargs", "children", "registered", "tainted",
                 "reachable", "reason", "_bound_names")

    def __init__(self, module, node, qualname, parent, class_name):
        self.module = module
        self.node = node
        self.name = node.name
        self.qualname = qualname
        self.parent = parent          # enclosing FunctionInfo or None
        self.class_name = class_name  # immediate class, or None
        a = node.args
        self.pos_params = [p.arg for p in a.posonlyargs + a.args]
        ndef = len(a.defaults)
        self.no_default_params = self.pos_params[:len(self.pos_params) - ndef]
        self.has_varargs = a.vararg is not None
        self.children = {}            # nested def name -> FunctionInfo
        self.registered = None        # register_op metadata dict
        self.tainted = set()          # names possibly holding tracers
        self.reachable = False
        self.reason = None
        self._bound_names = None

    def bound_names(self):
        """Names bound inside this function (params, assignments, for
        targets, nested defs, imports) — used to stop closure taint at
        shadowing bindings."""
        if self._bound_names is None:
            bound = set(self.pos_params)
            a = self.node.args
            bound.update(p.arg for p in a.kwonlyargs)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            for n in body_walk(self.node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    bound.add(n.name)
                elif isinstance(n, (ast.Import, ast.ImportFrom)):
                    for al in n.names:
                        bound.add((al.asname or al.name).split(".")[0])
            self._bound_names = bound
        return self._bound_names

    def __repr__(self):
        return "FunctionInfo(%s:%s)" % (self.module.relpath, self.qualname)


class ModuleInfo:
    __slots__ = ("path", "relpath", "modname", "tree", "lines",
                 "imports", "toplevel", "classes", "functions", "is_pkg")

    def __init__(self, path, relpath, modname, tree, lines, is_pkg=False):
        self.path = path
        self.relpath = relpath
        self.modname = modname      # dotted, e.g. "mxnet_tpu.ops.nn"
        self.tree = tree
        self.lines = lines
        self.is_pkg = is_pkg
        self.imports = {}           # local alias -> dotted target
        self.toplevel = {}          # name -> FunctionInfo
        self.classes = {}           # class name -> {method -> FunctionInfo}
        self.functions = []         # every FunctionInfo, any nesting


def body_walk(func_node):
    """Walk a function body WITHOUT descending into nested defs (they
    are separate FunctionInfos) — lambda bodies stay in, since they run
    in the enclosing trace context."""
    stack = list(func_node.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # decorators/defaults evaluate in this scope; body does not
            stack.extend(n.decorator_list)
            stack.extend(d for d in n.args.defaults if d is not None)
            stack.extend(d for d in n.args.kw_defaults if d is not None)
            continue
        stack.extend(ast.iter_child_nodes(n))


def module_level_walk(tree):
    """Walk statements that execute at import time: module body and
    class bodies, including function decorators and default-argument
    expressions — but not function/lambda bodies."""
    stack = list(tree.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(n.decorator_list)
            stack.extend(d for d in n.args.defaults if d is not None)
            stack.extend(d for d in n.args.kw_defaults if d is not None)
            continue
        if isinstance(n, ast.Lambda):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class _IndexVisitor(ast.NodeVisitor):
    """Collects imports, functions (any nesting) and classes."""

    def __init__(self, module):
        self.m = module
        self.func_stack = []   # FunctionInfo stack
        self.class_stack = []  # class name stack

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node):
        for al in node.names:
            if al.asname:
                self.m.imports[al.asname] = al.name
            else:
                # "import a.b" binds "a"
                top = al.name.split(".")[0]
                self.m.imports[top] = top
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        base = node.module or ""
        if node.level:  # relative: resolve against this module's package
            # a package __init__ IS its package: level 1 strips nothing
            strip = node.level - 1 if self.m.is_pkg else node.level
            parts = self.m.modname.split(".")
            pkg_parts = parts[:len(parts) - strip] if strip else parts
            base = ".".join(pkg_parts + ([base] if base else []))
        for al in node.names:
            if al.name == "*":
                continue
            target = "%s.%s" % (base, al.name) if base else al.name
            self.m.imports[al.asname or al.name] = target
        self.generic_visit(node)

    # -- defs -------------------------------------------------------------
    def _enter_func(self, node):
        parent = self.func_stack[-1] if self.func_stack else None
        cls = self.class_stack[-1] if self.class_stack else None
        if parent is not None:
            qual = parent.qualname + ".<locals>." + node.name
        elif cls is not None:
            qual = cls + "." + node.name
        else:
            qual = node.name
        fi = FunctionInfo(self.m, node, qual, parent, cls)
        self.m.functions.append(fi)
        if parent is not None:
            parent.children[node.name] = fi
        elif cls is not None:
            self.m.classes.setdefault(cls, {})[node.name] = fi
        else:
            self.m.toplevel[node.name] = fi
        return fi

    def visit_FunctionDef(self, node):
        fi = self._enter_func(node)
        self.func_stack.append(fi)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        # only track classes outside functions (methods of local classes
        # are rarely trace entry points)
        if self.func_stack:
            self.generic_visit(node)
            return
        self.class_stack.append(node.name)
        self.m.classes.setdefault(node.name, {})
        self.generic_visit(node)
        self.class_stack.pop()


class ProjectIndex:
    """All modules under the scanned roots + the jit-reachability graph."""

    #: jax.jit spellings: "<alias>.jit" where alias resolves to jax, or a
    #: bare name imported from jax.
    def __init__(self):
        self.modules = []           # ModuleInfo list
        self.by_modname = {}        # dotted modname -> ModuleInfo

    # -- construction -----------------------------------------------------
    @classmethod
    def build(cls, files, root_base):
        """files: iterable of absolute paths; root_base: directory the
        DISPLAY relpaths are computed against (the scan roots' parent,
        usually the repo root).  Dotted module names are computed
        independently, by ascending from each file past ``__init__.py``
        package dirs — so they stay import-accurate (and cross-module
        ``from mxnet_tpu.x import f`` edges resolve) no matter what
        directory the scan was rooted at."""
        idx = cls()
        for path in files:
            try:
                src = open(path, encoding="utf-8").read()
                tree = ast.parse(src, filename=path)
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
            rel = os.path.relpath(path, root_base)
            pkg_base = os.path.dirname(path)
            while os.path.exists(os.path.join(pkg_base, "__init__.py")):
                parent = os.path.dirname(pkg_base)
                if parent == pkg_base:
                    break
                pkg_base = parent
            modname = os.path.relpath(path, pkg_base)[:-3] \
                .replace(os.sep, ".")
            is_pkg = modname.endswith(".__init__") or modname == "__init__"
            if modname.endswith(".__init__"):
                modname = modname[:-len(".__init__")]
            m = ModuleInfo(path, rel.replace(os.sep, "/"), modname, tree,
                           src.splitlines(), is_pkg=is_pkg)
            _IndexVisitor(m).visit(tree)
            idx.modules.append(m)
            idx.by_modname[modname] = m
        idx._seed()
        idx._propagate()
        return idx

    # -- name resolution --------------------------------------------------
    def _project_module(self, dotted):
        """ModuleInfo for a dotted import target if it is in-project."""
        if dotted in self.by_modname:
            return self.by_modname[dotted]
        return None

    def resolve_name(self, module, scope, name):
        """Resolve a bare name to a FunctionInfo: enclosing nested defs,
        module top level, then from-imports of project modules."""
        fi = scope
        while fi is not None:
            if name in fi.children:
                return fi.children[name]
            fi = fi.parent
        if name in module.toplevel:
            return module.toplevel[name]
        target = module.imports.get(name)
        if target and "." in target:
            mod, _, attr = target.rpartition(".")
            pm = self._project_module(mod)
            if pm is not None and attr in pm.toplevel:
                return pm.toplevel[attr]
        return None

    def resolve_callee(self, module, scope, func_expr):
        """FunctionInfo for a call/reference target expression, or None."""
        if isinstance(func_expr, ast.Name):
            return self.resolve_name(module, scope, func_expr.id)
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and scope is not None \
                        and scope.class_name:
                    methods = module.classes.get(scope.class_name, {})
                    return methods.get(func_expr.attr)
                target = module.imports.get(base.id)
                if target:
                    pm = self._project_module(target)
                    if pm is not None:
                        return pm.toplevel.get(func_expr.attr)
        return None

    def is_jax_jit(self, module, expr):
        """True if *expr* denotes jax.jit under this module's imports."""
        d = dotted_name(expr)
        if d is None:
            return False
        if "." in d:
            head, _, tail = d.partition(".")
            return module.imports.get(head) == "jax" and tail == "jit"
        return module.imports.get(d) == "jax.jit"

    # -- seeding ----------------------------------------------------------
    def _register_op_meta(self, module, fi):
        """Metadata dict if fi is decorated @register_op(...), else None."""
        for dec in fi.node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            d = dotted_name(dec.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            if last != "register_op":
                continue
            meta = {"decorator": dec, "op_name": None, "needs_rng": False,
                    "donate": None, "num_outputs": 1, "input_names": None}
            if dec.args and isinstance(dec.args[0], ast.Constant):
                meta["op_name"] = dec.args[0].value
            for kw in dec.keywords:
                if kw.arg == "needs_rng" and isinstance(kw.value,
                                                        ast.Constant):
                    meta["needs_rng"] = bool(kw.value.value)
                elif kw.arg == "donate":
                    meta["donate"] = literal_int_tuple(kw.value)
                    meta["donate_node"] = kw.value
                elif kw.arg == "num_outputs":
                    if isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, int):
                        meta["num_outputs"] = kw.value.value
                    else:
                        meta["num_outputs"] = None  # callable/indeterminate
                elif kw.arg == "input_names":
                    meta["input_names"] = kw.value
            return meta
        return None

    def _jit_static_excludes(self, call):
        """Param indices/names excluded from tracing by static_arg*."""
        idxs, names = (), ()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                idxs = literal_int_tuple(kw.value) or ()
            elif kw.arg == "static_argnames":
                if isinstance(kw.value, ast.Constant):
                    names = (kw.value.value,)
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    names = tuple(e.value for e in kw.value.elts
                                  if isinstance(e, ast.Constant))
        return idxs, names

    def _mark(self, fi, reason, tainted):
        changed = not fi.reachable
        if not fi.reachable:
            fi.reachable = True
            fi.reason = reason
        new = tainted - fi.tainted
        if new:
            fi.tainted.update(new)
            changed = True
        return changed

    def _taint_all_params(self, fi, skip_idxs=(), skip_names=()):
        return {p for i, p in enumerate(fi.pos_params)
                if i not in skip_idxs and p not in skip_names}

    def _seed(self):
        self._worklist = []
        for m in self.modules:
            for fi in m.functions:
                meta = self._register_op_meta(m, fi)
                if meta is not None:
                    fi.registered = meta
                    inputs = list(fi.no_default_params)
                    if meta["needs_rng"] and inputs:
                        inputs = inputs[1:]
                    if self._mark(fi, "register_op(%s)" % (meta["op_name"],),
                                  set(inputs)):
                        self._worklist.append(fi)
            # jax.jit sites anywhere in the module
            for fi_scope, call in self._iter_calls(m):
                if not (isinstance(call, ast.Call)
                        and self.is_jax_jit(m, call.func) and call.args):
                    continue
                target = self.resolve_callee(m, fi_scope, call.args[0])
                if target is None:
                    continue
                idxs, names = self._jit_static_excludes(call)
                if self._mark(target, "jax.jit site %s:%d"
                              % (m.relpath, call.lineno),
                              self._taint_all_params(target, idxs, names)):
                    self._worklist.append(target)
            # @jax.jit / @partial(jax.jit, ...) decorators
            for fi in m.functions:
                for dec in fi.node.decorator_list:
                    idxs, names = (), ()
                    hit = False
                    if self.is_jax_jit(m, dec):
                        hit = True
                    elif isinstance(dec, ast.Call):
                        if self.is_jax_jit(m, dec.func):
                            hit = True
                            idxs, names = self._jit_static_excludes(dec)
                        else:
                            d = dotted_name(dec.func)
                            if d and d.rsplit(".", 1)[-1] == "partial" \
                                    and dec.args \
                                    and self.is_jax_jit(m, dec.args[0]):
                                hit = True
                                idxs, names = self._jit_static_excludes(dec)
                    if hit and self._mark(
                            fi, "@jax.jit %s:%d" % (m.relpath, fi.node.lineno),
                            self._taint_all_params(fi, idxs, names)):
                        self._worklist.append(fi)

    def _iter_calls(self, module):
        """Yield (enclosing FunctionInfo or None, Call node) pairs."""
        # module level (incl. class bodies)
        for n in module_level_walk(module.tree):
            if isinstance(n, ast.Call):
                yield None, n
        for fi in module.functions:
            for n in body_walk(fi.node):
                if isinstance(n, ast.Call):
                    yield fi, n

    # -- propagation ------------------------------------------------------
    def _arg_tainted(self, fi, expr):
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in fi.tainted:
                return True
        return False

    def _propagate(self):
        work = list(self._worklist)
        del self._worklist
        guard = 0
        while work and guard < 100000:
            guard += 1
            fi = work.pop()
            m = fi.module
            for n in body_walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                # direct call edge with positional/keyword taint mapping
                callee = self.resolve_callee(m, fi, n.func)
                if callee is not None:
                    tainted = set()
                    for i, a in enumerate(n.args):
                        if isinstance(a, ast.Starred):
                            break
                        if i < len(callee.pos_params) and \
                                self._arg_tainted(fi, a):
                            tainted.add(callee.pos_params[i])
                    for kw in n.keywords:
                        if kw.arg and kw.arg in callee.pos_params and \
                                self._arg_tainted(fi, kw.value):
                            tainted.add(kw.arg)
                    if self._mark(callee, "called from %s" % fi.qualname,
                                  tainted):
                        work.append(callee)
                # function VALUES passed into combinators
                # (lax.scan/cond/while_loop/custom_vjp/...) become trace
                # entry points with every parameter possibly traced
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if isinstance(a, (ast.Name, ast.Attribute)) \
                            and a is not n.func:
                        ref = self.resolve_callee(m, fi, a)
                        if ref is not None and ref is not callee:
                            if self._mark(ref, "passed as callback from %s"
                                          % fi.qualname,
                                          self._taint_all_params(ref)):
                                work.append(ref)
            # closure taint: nested defs see the parent's tainted names
            # unless they rebind them
            for child in fi.children.values():
                inherit = (fi.tainted - child.bound_names()) \
                    if child.reachable else set()
                if child.reachable and inherit and \
                        self._mark(child, child.reason, inherit):
                    work.append(child)

    # -- queries used by rules -------------------------------------------
    def reachable_functions(self):
        for m in self.modules:
            for fi in m.functions:
                if fi.reachable:
                    yield fi

    def registered_functions(self):
        for m in self.modules:
            for fi in m.functions:
                if fi.registered is not None:
                    yield fi
