"""graftlint engine: file discovery, suppressions, baseline, reporting.

Pure stdlib.  The engine parses every ``*.py`` under the scan roots
once, hands the :class:`ProjectIndex` to each rule, then filters the
findings through per-line suppressions and the committed baseline.

Suppression syntax (same line as the finding)::

    risky_thing()  # graftlint: disable=JG001
    other_thing()  # graftlint: disable=JG003,JG004
    anything()     # graftlint: disable=all

Baseline workflow: pre-existing findings live in a committed JSON file
keyed by (rule, path, normalized source line) — stable across
unrelated line-number drift.  ``--update-baseline`` rewrites it from
the current findings; CI fails on any finding NOT in the baseline, so
the count can only go down.
"""

from __future__ import annotations

import json
import os
import re
import time

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+|all)")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
BASELINE_VERSION = 1


class Finding:
    __slots__ = ("rule", "path", "line", "col", "message", "status")

    def __init__(self, rule, path, line, col, message):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.status = "new"     # new | baselined | suppressed

    def fingerprint(self, source_line=""):
        return "%s|%s|%s" % (self.rule, self.path,
                             " ".join(source_line.split()))

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "status": self.status}

    def __repr__(self):
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col,
                                    self.rule, self.message)


class Baseline:
    """Committed ledger of accepted pre-existing findings."""

    def __init__(self, path=DEFAULT_BASELINE):
        self.path = path
        self.counts = {}    # fingerprint -> accepted count

    @classmethod
    def load(cls, path=DEFAULT_BASELINE):
        b = cls(path)
        if path and os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            b.counts = dict(data.get("findings", {}))
        return b

    def save(self, findings, lines_of):
        entries = {}
        for f in findings:
            fp = f.fingerprint(lines_of(f))
            entries[fp] = entries.get(fp, 0) + 1
        payload = {
            "version": BASELINE_VERSION,
            "comment": "accepted pre-existing graftlint findings; "
                       "regenerate with --update-baseline (see "
                       "docs/static_analysis.md)",
            "findings": dict(sorted(entries.items())),
        }
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=False)
            f.write("\n")

    def consume(self, finding, source_line):
        """True (and decrement) if the finding is baselined."""
        fp = finding.fingerprint(source_line)
        left = self.counts.get(fp, 0)
        if left > 0:
            self.counts[fp] = left - 1
            return True
        return False


def parse_suppressions(lines):
    """{lineno: set of rule ids (or {'all'})} for one file."""
    sup = {}
    for i, line in enumerate(lines, 1):
        mark = line.find("#")
        if mark < 0 or "graftlint" not in line[mark:]:
            continue
        mobj = _SUPPRESS_RE.search(line, mark)
        if not mobj:
            continue
        spec = mobj.group(1).strip()
        if spec.lower() == "all":
            sup[i] = {"all"}
        else:
            sup[i] = {s.strip().upper() for s in spec.split(",")
                      if s.strip()}
    return sup


class LintEngine:
    def __init__(self, paths, rules=None, baseline_path=DEFAULT_BASELINE,
                 use_baseline=True):
        from .rules import ALL_RULES
        self.paths = [os.path.abspath(p) for p in paths]
        self.rule_ids = sorted(rules or ALL_RULES)
        self.rules = {rid: ALL_RULES[rid] for rid in self.rule_ids}
        self.baseline_path = baseline_path
        self.use_baseline = use_baseline
        self.project = None
        self.stats = {}

    # -- discovery --------------------------------------------------------
    def _discover(self):
        files = []
        for p in self.paths:
            if not os.path.exists(p):
                # a missing path must fail loudly: a typo'd/renamed CI
                # target would otherwise lint nothing and stay green
                raise FileNotFoundError(
                    "graftlint: scan path does not exist: %s" % p)
            if os.path.isfile(p) and p.endswith(".py"):
                files.append(p)
            elif os.path.isdir(p):
                for base, dirs, names in os.walk(p):
                    dirs[:] = sorted(d for d in dirs
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                    files.extend(os.path.join(base, n)
                                 for n in sorted(names)
                                 if n.endswith(".py"))
        if not files:
            raise FileNotFoundError(
                "graftlint: no .py files under %s" % ", ".join(self.paths))
        return files

    def _root_base(self):
        """Directory module names/relpaths are computed against: the
        parent of the TOP enclosing package of each scan root, so
        ``graftlint mxnet_tpu/executor.py`` still sees the relpath
        ``mxnet_tpu/executor.py`` and modname ``mxnet_tpu.executor``
        (dispatch-path scoping and cross-module resolution depend on
        it), not a bare ``executor.py``."""
        bases = set()
        for p in self.paths:
            # start from the directory whose name should NOT appear in
            # relpaths: a scanned dir's parent, or a file's directory
            d = os.path.dirname(p)
            # then ascend past package dirs (__init__.py) to the top
            while os.path.exists(os.path.join(d, "__init__.py")):
                parent = os.path.dirname(d)
                if parent == d:
                    break
                d = parent
            bases.add(d or os.getcwd())
        return os.path.commonpath(sorted(bases)) if bases \
            else os.getcwd()

    # -- run --------------------------------------------------------------
    def run(self):
        from .callgraph import ProjectIndex
        t0 = time.perf_counter()
        files = self._discover()
        self.project = ProjectIndex.build(files, self._root_base())
        lines_by_path = {m.relpath: m.lines for m in self.project.modules}
        sup_by_path = {m.relpath: parse_suppressions(m.lines)
                       for m in self.project.modules}

        findings = []
        for rid in self.rule_ids:
            findings.extend(self.rules[rid](self.project))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

        def src_line(f):
            lines = lines_by_path.get(f.path, ())
            return lines[f.line - 1] if 0 < f.line <= len(lines) else ""

        self._src_line = src_line
        baseline = Baseline.load(self.baseline_path) if self.use_baseline \
            else Baseline(self.baseline_path)

        n_sup = n_base = 0
        for f in findings:
            sup = sup_by_path.get(f.path, {}).get(f.line, ())
            if "all" in sup or f.rule in sup:
                f.status = "suppressed"
                n_sup += 1
            elif baseline.consume(f, src_line(f)):
                f.status = "baselined"
                n_base += 1

        new = [f for f in findings if f.status == "new"]
        self.stats = {
            "files": len(self.project.modules),
            "rules": len(self.rule_ids),
            "findings": len(findings),
            "suppressed": n_sup,
            "baselined": n_base,
            "new": len(new),
            "seconds": round(time.perf_counter() - t0, 3),
        }
        return findings

    def update_baseline(self, findings):
        """Accept every current non-suppressed finding into the baseline."""
        keep = [f for f in findings if f.status != "suppressed"]
        Baseline(self.baseline_path).save(keep, self._src_line)
        return len(keep)

    # -- reporting --------------------------------------------------------
    def summary_line(self):
        s = self.stats
        return ("graftlint: files=%d rules=%d findings=%d baselined=%d "
                "suppressed=%d new=%d time=%.2fs"
                % (s["files"], s["rules"], s["findings"], s["baselined"],
                   s["suppressed"], s["new"], s["seconds"]))

    def report_text(self, findings, show_all=False):
        out = []
        for f in findings:
            if f.status == "new" or show_all:
                tag = "" if f.status == "new" else " [%s]" % f.status
                out.append("%s:%d:%d: %s%s %s"
                           % (f.path, f.line, f.col, f.rule, tag, f.message))
        return "\n".join(out)

    def report_json(self, findings):
        return json.dumps({"summary": self.stats,
                           "findings": [f.as_dict() for f in findings]},
                          indent=1)
