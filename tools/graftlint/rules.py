"""graftlint rules JG001–JG009.

Each rule is a function ``check(project) -> list[Finding]`` over the
:class:`~tools.graftlint.callgraph.ProjectIndex`.  Rules never import
the analyzed code; everything is decided from the AST plus the
jit-reachability/taint graph.
"""

from __future__ import annotations

import ast
import re

from .callgraph import (body_walk, dotted_name, literal_int_tuple,
                        module_level_walk)
from .engine import Finding

#: modules whose exception handling sits on the dispatch path between
#: user code and jax — a silent broad except there eats the very
#: jax.errors a user needs to see (JG006 scope)
DISPATCH_PREFIXES = (
    "mxnet_tpu/executor.py", "mxnet_tpu/grouped_executor.py",
    "mxnet_tpu/autograd.py", "mxnet_tpu/capi_bridge.py",
    "mxnet_tpu/ops/registry.py", "mxnet_tpu/module/",
    "mxnet_tpu/optimizer/", "mxnet_tpu/symbol/", "mxnet_tpu/ndarray/",
    "mxnet_tpu/parallel/",
    # threaded subsystems: a swallowed exception here doesn't just eat
    # jax.errors — it eats graftsan sanitizer reports and leaves a
    # worker blocked on a peer that silently died
    "mxnet_tpu/_kvstore_impl.py", "mxnet_tpu/kvstore_server.py",
    "mxnet_tpu/io/io.py", "mxnet_tpu/gluon/data/dataloader.py",
    "mxnet_tpu/runtime/engine.py",
)

#: jax top-level calls that force backend/device initialization (JG008)
_JAX_INIT_CALLS = {
    "jax.devices", "jax.device_count", "jax.local_devices",
    "jax.local_device_count", "jax.default_backend", "jax.device_put",
    "jax.random.PRNGKey",
}

_RNG_PARAM_NAMES = {"rng", "key", "rng_key", "prng_key", "prng"}


def _f(rule, fi_or_module, node, msg):
    m = fi_or_module if not hasattr(fi_or_module, "module") \
        else fi_or_module.module
    return Finding(rule, m.relpath, node.lineno,
                   getattr(node, "col_offset", 0), msg)


def _resolves_to_module(module, expr, dotted_targets):
    """True if expr's dotted path, after import-alias resolution of its
    root, starts with one of *dotted_targets*."""
    d = dotted_name(expr)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    resolved = module.imports.get(head)
    if resolved is None:
        return False
    full = resolved + ("." + tail if tail else "")
    return any(full == t or full.startswith(t + ".")
               for t in dotted_targets)


# ---------------------------------------------------------------------------
# JG001 — host materialization of possibly-traced values
# ---------------------------------------------------------------------------

_HOST_COERCIONS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist", "numpy", "asnumpy"}


def check_jg001(project):
    out = []
    for fi in project.reachable_functions():
        if not fi.tainted:
            continue
        m = fi.module
        for n in body_walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            # float(x) / int(x) / bool(x) on a tainted name
            if isinstance(n.func, ast.Name) and \
                    n.func.id in _HOST_COERCIONS and n.args and \
                    isinstance(n.args[0], ast.Name) and \
                    n.args[0].id in fi.tainted:
                out.append(_f("JG001", fi, n,
                              "%s(%s) materializes a possibly-traced value "
                              "on host inside jit-reachable '%s' (%s); "
                              "this raises ConcretizationTypeError under "
                              "trace — keep it device-side (jnp) or hoist "
                              "it out of the traced path"
                              % (n.func.id, n.args[0].id, fi.qualname,
                                 fi.reason)))
            # x.item() / x.tolist() / x.numpy() on a tainted name
            elif isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _HOST_METHODS and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id in fi.tainted:
                out.append(_f("JG001", fi, n,
                              "%s.%s() forces a device->host round-trip on "
                              "a possibly-traced value inside "
                              "jit-reachable '%s' (%s)"
                              % (n.func.value.id, n.func.attr, fi.qualname,
                                 fi.reason)))
            # np.asarray(x) / np.array(x) on a tainted name
            elif isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("asarray", "array") and \
                    _resolves_to_module(m, n.func, ("numpy",)) and \
                    n.args and isinstance(n.args[0], ast.Name) and \
                    n.args[0].id in fi.tainted:
                out.append(_f("JG001", fi, n,
                              "np.%s(%s) copies a possibly-traced value to "
                              "host inside jit-reachable '%s' — use jnp"
                              % (n.func.attr, n.args[0].id, fi.qualname)))
    return out


# ---------------------------------------------------------------------------
# JG002 — use after donation
# ---------------------------------------------------------------------------

def _donated_positions(call, scope_literals):
    """Donated argnums of a jax.jit(...) call: tuple of ints, 'all' when
    donating but positions are indeterminate, or None when not donating."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        lit = literal_int_tuple(v)
        if lit is not None:
            return lit or None          # empty tuple donates nothing
        if isinstance(v, ast.Name) and v.id in scope_literals:
            lit = scope_literals[v.id]
            return lit or None
        if isinstance(v, ast.IfExp):
            # the `(0, 4) if supports_donation() else ()` idiom: the
            # truthy branch is what donates on TPU
            lit = literal_int_tuple(v.body)
            if lit is not None:
                return lit or None
        return "all"
    return None


class _OrderedEvents(ast.NodeVisitor):
    """Emit (kind, name, node) events of one function body in
    evaluation order: 'load', 'store', 'call' (call of a tracked
    name).  Nested defs are skipped; control flow is linearized (a
    linter approximation — branches are treated as sequential)."""

    def __init__(self):
        self.events = []

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self.visit(t)

    def visit_AugAssign(self, node):
        # target is read, value evaluated, target stored
        if isinstance(node.target, ast.Name):
            self.events.append(("load", node.target.id, node.target))
        self.visit(node.value)
        if isinstance(node.target, ast.Name):
            self.events.append(("store", node.target.id, node.target))

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
        self.visit(node.target)

    def visit_Call(self, node):
        self.visit(node.func)
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)
        self.events.append(("call", None, node))

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.events.append(("store", node.id, node))
        elif isinstance(node.ctx, ast.Load):
            self.events.append(("load", node.id, node))


def check_jg002(project):
    out = []
    for m in project.modules:
        for fi in m.functions:
            out.extend(_jg002_scope(project, m, fi))
    return out


def _jg002_scope(project, m, fi):
    ev = _OrderedEvents()
    for stmt in fi.node.body:
        ev.visit(stmt)
    events = ev.events

    # pre-pass A: literal int-tuple bindings (for donate_argnums=<name>)
    scope_literals = {}
    # pre-pass B: names assigned from jax.jit(..., donate_argnums=...)
    assigned_jits = {}  # target name -> donated positions
    for stmt in fi.node.body:
        for n in ast.walk(stmt):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                continue
            lit = literal_int_tuple(n.value)
            if lit is None and isinstance(n.value, ast.IfExp):
                lit = literal_int_tuple(n.value.body)
            if lit is not None:
                scope_literals[n.targets[0].id] = lit
            if isinstance(n.value, ast.Call) and \
                    project.is_jax_jit(m, n.value.func):
                pos = _donated_positions(n.value, scope_literals)
                if pos is not None:
                    assigned_jits[n.targets[0].id] = pos

    def report(name, node, dcall, callee):
        return _f(
            "JG002", m, node,
            "'%s' was donated to '%s' at line %d and is read afterwards "
            "— its buffer is invalid after the donating call (XLA reuses "
            "it for the outputs); reorder the read, or rebind the name "
            "to the call's result" % (name, callee, dcall.lineno))

    findings = []
    donated = {}   # arg name -> (donating call node, callee label)
    for kind, name, node in events:
        if kind == "call":
            call = node
            # invocation of a name bound to a donating jit in this scope
            if isinstance(call.func, ast.Name) and \
                    call.func.id in assigned_jits:
                pos = assigned_jits[call.func.id]
                idxs = range(len(call.args)) if pos == "all" else pos
                for i in idxs:
                    if i < len(call.args) and \
                            isinstance(call.args[i], ast.Name):
                        donated[call.args[i].id] = (call, call.func.id)
            # inline jax.jit(f, donate_argnums=...)(args)
            elif isinstance(call.func, ast.Call) and \
                    project.is_jax_jit(m, call.func.func):
                pos = _donated_positions(call.func, scope_literals)
                if pos is not None:
                    idxs = range(len(call.args)) if pos == "all" else pos
                    for i in idxs:
                        if i < len(call.args) and \
                                isinstance(call.args[i], ast.Name):
                            donated[call.args[i].id] = (call, "<inline jit>")
        elif kind == "store":
            # rebinding a donated name makes later reads safe again
            donated.pop(name, None)
        elif kind == "load":
            if name in donated:
                dcall, callee = donated.pop(name)  # one report / donation
                findings.append(report(name, node, dcall, callee))
    return findings


# ---------------------------------------------------------------------------
# JG003 — side effects under trace
# ---------------------------------------------------------------------------

_SIDE_EFFECT_MODULES = ("mxnet_tpu.profiler", "logging", "warnings")


def check_jg003(project):
    out = []
    for fi in project.reachable_functions():
        m = fi.module
        stored = {n.id for n in body_walk(fi.node)
                  if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}
        for n in body_walk(fi.node):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Name) and n.func.id == "print":
                    out.append(_f("JG003", fi, n,
                                  "print() inside jit-reachable '%s' fires "
                                  "once at trace time and never again — "
                                  "use jax.debug.print for per-step output"
                                  % fi.qualname))
                elif _resolves_to_module(m, n.func, _SIDE_EFFECT_MODULES):
                    out.append(_f("JG003", fi, n,
                                  "'%s' inside jit-reachable '%s' runs at "
                                  "trace time only (cached executions skip "
                                  "the Python body) — counters/log lines "
                                  "here silently under-report"
                                  % (dotted_name(n.func), fi.qualname)))
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                written = [nm for nm in n.names if nm in stored]
                if written:
                    kw = "global" if isinstance(n, ast.Global) else "nonlocal"
                    out.append(_f("JG003", fi, n,
                                  "%s write to %s inside jit-reachable '%s' "
                                  "mutates host state at trace time only — "
                                  "the compiled program never re-runs it"
                                  % (kw, ", ".join(written), fi.qualname)))
    return out


# ---------------------------------------------------------------------------
# JG004 — recompile hazards
# ---------------------------------------------------------------------------

_IMPURE_MODULES = ("time", "random", "datetime")


def check_jg004(project):
    out = []
    for fi in project.reachable_functions():
        m = fi.module
        for n in body_walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            d = dotted_name(n.func)
            if d is None:
                continue
            if _resolves_to_module(m, n.func, _IMPURE_MODULES):
                out.append(_f("JG004", fi, n,
                              "'%s' inside jit-reachable '%s' is evaluated "
                              "at trace time: its value is burned into the "
                              "compiled program as a constant (and a fresh "
                              "value forces a retrace)" % (d, fi.qualname)))
            elif ".random." in ("." + d + ".") and \
                    _resolves_to_module(m, n.func, ("numpy",)):
                out.append(_f("JG004", fi, n,
                              "np.random call '%s' inside jit-reachable "
                              "'%s' is host-side and trace-time-only — use "
                              "jax.random with an explicit key"
                              % (d, fi.qualname)))
    # jax.jit inside a loop body: a fresh wrapper per iteration defeats
    # the jit cache (cache key includes function identity) -> retrace
    # and recompile every iteration
    for m in project.modules:
        for scope, call in m_loop_jits(project, m):
            out.append(Finding(
                "JG004", m.relpath, call.lineno, call.col_offset,
                "jax.jit called inside a loop: each iteration builds a "
                "fresh jitted callable whose cache is empty, so every "
                "call retraces and recompiles — hoist the jit out of "
                "the loop"))
    # unhashable literal passed at a static_argnums position of an
    # inline jit call — TypeError at call time, statically determinable
    for m in project.modules:
        for fi_scope, call in project._iter_calls(m):
            if not (isinstance(call.func, ast.Call)
                    and project.is_jax_jit(m, call.func.func)):
                continue
            idxs, _names = project._jit_static_excludes(call.func)
            for i in idxs:
                if i < len(call.args) and \
                        isinstance(call.args[i], (ast.List, ast.Dict,
                                                  ast.Set)):
                    out.append(Finding(
                        "JG004", m.relpath, call.args[i].lineno,
                        call.args[i].col_offset,
                        "unhashable %s literal passed at static_argnums "
                        "position %d — static args must be hashable (use "
                        "a tuple), else every call raises/retraces"
                        % (type(call.args[i]).__name__.lower(), i)))
    return out


def m_loop_jits(project, m):
    """(scope, jax.jit Call) pairs lexically inside for/while bodies —
    a function def inside the loop resets the context (its body runs
    when called, not per loop iteration)."""
    hits = []

    def scan(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                scan(child, False)
                continue
            child_in_loop = in_loop or isinstance(node, (ast.For, ast.While))
            if isinstance(child, ast.Call) and child_in_loop and \
                    project.is_jax_jit(m, child.func):
                hits.append((None, child))
            scan(child, child_in_loop)

    scan(m.tree, False)
    return hits


# ---------------------------------------------------------------------------
# JG005 — register_op contract violations
# ---------------------------------------------------------------------------

def check_jg005(project):
    out = []
    for fi in project.registered_functions():
        meta = fi.registered
        node = fi.node
        arity_params = list(fi.no_default_params)
        if meta["needs_rng"]:
            if not arity_params or \
                    arity_params[0] not in _RNG_PARAM_NAMES:
                out.append(_f("JG005", fi, node,
                              "op '%s' declares needs_rng=True but '%s' "
                              "does not take an rng key as first "
                              "positional parameter (got %s) — the "
                              "runtime passes the key positionally"
                              % (meta["op_name"], fi.name,
                                 arity_params[:1] or "nothing")))
            arity_params = arity_params[1:]
        n_inputs = len(arity_params)
        # declared input_names may legally extend past the required
        # positionals with optional array inputs (Convolution's
        # bias=None) — those are donatable too, matching the runtime
        # mirror registry.op_contract
        n_donatable = n_inputs
        names_node = meta.get("input_names")
        if isinstance(names_node, (ast.Tuple, ast.List)):
            n_donatable = max(n_donatable, len(names_node.elts))
        donate = meta.get("donate")
        if donate:
            if fi.has_varargs:
                pass  # arity indeterminate
            else:
                for i in donate:
                    if i < 0 or i >= n_donatable:
                        out.append(_f(
                            "JG005", fi, meta.get("donate_node", node),
                            "op '%s': donate index %d is out of range for "
                            "%d donatable array input(s) %s — donation "
                            "would alias a nonexistent buffer"
                            % (meta["op_name"], i, n_donatable,
                               tuple(arity_params))))
        n_out = meta["num_outputs"]
        if isinstance(n_out, int):
            arities = _return_arities(node)
            if arities is not None and arities and \
                    all(a == arities[0] for a in arities) and \
                    arities[0] != n_out:
                out.append(_f("JG005", fi, node,
                              "op '%s' declares num_outputs=%d but '%s' "
                              "statically returns %d value(s) — the "
                              "executor would mis-split the outputs"
                              % (meta["op_name"], n_out, fi.name,
                                 arities[0])))
    return out


def _return_arities(func_node):
    """Arity of each return when ALL are statically determinable tuple
    literals (or single non-tuple expressions -> arity 1); None when any
    return is indeterminate."""
    arities = []
    for n in body_walk(func_node):
        if not isinstance(n, ast.Return):
            continue
        v = n.value
        if v is None:
            return None
        if isinstance(v, ast.Tuple):
            arities.append(len(v.elts))
        elif isinstance(v, (ast.Name, ast.IfExp, ast.Starred)):
            return None  # could be anything
        elif isinstance(v, ast.Call):
            return None
        else:
            arities.append(1)
    return arities


# ---------------------------------------------------------------------------
# JG006 — silent overbroad exception handler in a dispatch path
# ---------------------------------------------------------------------------

def check_jg006(project):
    out = []
    for m in project.modules:
        if not any(m.relpath.startswith(p) or ("/" + p) in m.relpath
                   for p in DISPATCH_PREFIXES):
            continue
        for n in ast.walk(m.tree):
            if not isinstance(n, ast.ExceptHandler):
                continue
            broad = n.type is None or (
                isinstance(n.type, ast.Name)
                and n.type.id in ("Exception", "BaseException"))
            if not broad:
                continue
            if _handler_is_loud(n):
                continue
            what = "bare except:" if n.type is None \
                else "except %s:" % n.type.id
            out.append(Finding(
                "JG006", m.relpath, n.lineno, n.col_offset,
                "%s in a dispatch path swallows jax.errors silently — "
                "narrow the exception type, re-raise, or at minimum bind "
                "and log the exception so trace/compile failures stay "
                "diagnosable" % what))
    return out


def _handler_is_loud(handler):
    """A handler that re-raises, logs, or otherwise uses the caught
    exception is deliberate fallback handling, not a silent swallow."""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if handler.name and isinstance(n, ast.Name) and \
                n.id == handler.name and isinstance(n.ctx, ast.Load):
            return True
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            if d and ("log" in d.lower() or d.endswith("warn")):
                return True
    return False


# ---------------------------------------------------------------------------
# JG007 — mutable default argument in public API
# ---------------------------------------------------------------------------

def check_jg007(project):
    out = []
    for m in project.modules:
        for fi in m.functions:
            node = fi.node
            for d in list(node.args.defaults) + \
                    [x for x in node.args.kw_defaults if x is not None]:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set", "bytearray")
                    and not d.args and not d.keywords)
                if mutable:
                    if isinstance(d, ast.Call):
                        what = "%s()" % d.func.id
                    else:
                        what = type(d).__name__.lower()
                    public = "public API " if not fi.name.startswith("_") \
                        else ""
                    out.append(_f(
                        "JG007", fi, d,
                        "mutable default %s in %s'%s' is shared across "
                        "calls — one caller's mutation leaks into the "
                        "next; default to None and construct inside"
                        % (what, public, fi.qualname)))
    return out


# ---------------------------------------------------------------------------
# JG008 — backend-forcing jnp/jax call at module import time
# ---------------------------------------------------------------------------

def check_jg008(project):
    out = []
    for m in project.modules:
        for n in module_level_walk(m.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted_name(n.func)
            if d is None:
                continue
            head, _, tail = d.partition(".")
            resolved = m.imports.get(head)
            if resolved is None:
                continue
            full = resolved + ("." + tail if tail else "")
            is_jnp = full == "jax.numpy" or full.startswith("jax.numpy.")
            if is_jnp or full in _JAX_INIT_CALLS:
                out.append(Finding(
                    "JG008", m.relpath, n.lineno, n.col_offset,
                    "'%s' at module import time forces jax backend "
                    "initialization on import (device dial-out, several "
                    "seconds on TPU; breaks JAX_PLATFORMS overrides set "
                    "after import) — build the constant lazily inside "
                    "the op or cache it behind a function" % d))
    return out


# ---------------------------------------------------------------------------
# JG009 — non-atomic persistence write
# ---------------------------------------------------------------------------

#: a function counts as a persistence writer when its NAME says it
#: persists something...
_JG009_FUNC_RE = re.compile(
    r"save|dump|write|serial|export|checkpoint", re.IGNORECASE)
#: ...AND its name or any string literal in its body mentions a
#: checkpoint/state artifact
_JG009_TOKENS = (".params", ".states", "-symbol.json", "checkpoint",
                 "ckpt", "manifest")
#: the atomic writer implementation itself is the one place raw
#: open()-for-write on these paths is correct
_JG009_EXEMPT = ("mxnet_tpu/resilience/",)

_WRITE_MODE_CHARS = ("w", "a", "x")


def _jg009_write_mode(call):
    """The mode literal of an ``open()`` call when it opens for
    writing, else None."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and \
            any(c in mode for c in _WRITE_MODE_CHARS):
        return mode
    return None


def _jg009_is_persistence_writer(fi):
    if not _JG009_FUNC_RE.search(fi.name):
        return False
    hay = [fi.name.lower()]
    for n in body_walk(fi.node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            hay.append(n.value.lower())
    return any(tok in h for h in hay for tok in _JG009_TOKENS)


def check_jg009(project):
    out = []
    for m in project.modules:
        if any(p in m.relpath for p in _JG009_EXEMPT):
            continue
        for fi in m.functions:
            if not _jg009_is_persistence_writer(fi):
                continue
            for n in body_walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Name) and n.func.id == "open":
                    mode = _jg009_write_mode(n)
                    if mode is not None:
                        out.append(_f(
                            "JG009", fi, n,
                            "open(..., %r) in persistence writer '%s' "
                            "writes a checkpoint/state path in place — "
                            "a crash mid-write tears the only copy; "
                            "route it through resilience.checkpoint."
                            "atomic_write (tmp + fsync + os.replace)"
                            % (mode, fi.qualname)))
                elif isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("save", "savez",
                                        "savez_compressed") and \
                        _resolves_to_module(m, n.func, ("numpy",)):
                    out.append(_f(
                        "JG009", fi, n,
                        "np.%s in persistence writer '%s' streams a "
                        "checkpoint/state file in place — serialize to "
                        "bytes and hand them to resilience.checkpoint."
                        "atomic_write" % (n.func.attr, fi.qualname)))
                elif isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "dump" and \
                        _resolves_to_module(m, n.func,
                                            ("pickle", "json")):
                    out.append(_f(
                        "JG009", fi, n,
                        "%s.dump in persistence writer '%s' streams a "
                        "checkpoint/state file in place — use dumps() "
                        "and resilience.checkpoint.atomic_write"
                        % (dotted_name(n.func).split(".")[0],
                           fi.qualname)))
    return out


# ---------------------------------------------------------------------------
# JG010 — attribute written both with and without its guarding lock
# ---------------------------------------------------------------------------

#: calls whose result is a lock-like object when assigned to self.<attr>
_LOCK_FACTORY_ATTRS = {"Lock", "RLock", "Condition",
                       "lock", "rlock", "condition"}
_LOCK_FACTORY_MODULES = ("threading", "mxnet_tpu.sanitizer")


def _is_lock_factory(m, call):
    if not isinstance(call, ast.Call):
        return False
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _LOCK_FACTORY_ATTRS:
        return False
    return _resolves_to_module(m, call.func, _LOCK_FACTORY_MODULES)


def _self_attr(node):
    """'a' for a ``self.a`` Attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _attr_writes(func_node, lock_attrs):
    """(attr, node, frozenset of held self-lock attrs) for every
    ``self.attr = ...`` / ``self.attr[k] = ...`` / ``self.attr += ...``
    in *func_node*, tracking lexical ``with self.<lock>:`` nesting."""
    out = []

    def targets_of(stmt):
        if isinstance(stmt, ast.Assign):
            return stmt.targets
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            return [stmt.target]
        return []

    def lock_call(stmt):
        """('acquire'|'release', lockattr) for a bare
        self.<lock>.acquire()/.release() statement, else None."""
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)):
            return None
        f = stmt.value.func
        la = _self_attr(f.value)
        if la in lock_attrs and f.attr in ("acquire", "release"):
            return f.attr, la
        return None

    def scan(body, held):
        # linear acquire()/release() discipline at this nesting level:
        # the try/finally idiom (acquire; try: write; finally:
        # release) guards its try body just like a with-block would
        cur = list(held)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            lc = lock_call(stmt)
            if lc is not None:
                op, la = lc
                if op == "acquire":
                    cur = cur + [la]
                elif la in cur:
                    cur = [x for x in cur if x != la]
                continue
            for t in targets_of(stmt):
                base = t.value if isinstance(t, ast.Subscript) else t
                a = _self_attr(base)
                if a is not None and a not in lock_attrs:
                    out.append((a, t, frozenset(cur)))
            if isinstance(stmt, ast.With):
                acquired = []
                for item in stmt.items:
                    la = _self_attr(item.context_expr)
                    if la in lock_attrs:
                        acquired.append(la)
                scan(stmt.body, cur + acquired)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    scan(getattr(stmt, attr, []) or [], cur)
                for h in getattr(stmt, "handlers", []) or []:
                    scan(h.body, cur)
    scan(func_node.body, [])
    return out


def check_jg010(project):
    out = []
    for m in project.modules:
        for cls, methods in m.classes.items():
            # 1. the class's lock attributes
            lock_attrs = set()
            for fi in methods.values():
                for n in body_walk(fi.node):
                    if isinstance(n, ast.Assign) and \
                            _is_lock_factory(m, n.value):
                        for t in n.targets:
                            a = _self_attr(t)
                            if a is not None:
                                lock_attrs.add(a)
            if not lock_attrs:
                continue
            # 2. every non-__init__ write, with held-lock context
            writes = {}   # attr -> [(method, node, heldset)]
            for name, fi in methods.items():
                if name == "__init__":
                    continue    # construction is single-threaded
                for a, node, held in _attr_writes(fi.node, lock_attrs):
                    writes.setdefault(a, []).append((name, node, held))
            # 3. guarded somewhere + bare somewhere else => report bare
            for a, sites in writes.items():
                guarded = sorted({l for _, _, held in sites
                                  for l in held})
                if not guarded:
                    continue
                for name, node, held in sites:
                    if held:
                        continue
                    out.append(Finding(
                        "JG010", m.relpath, node.lineno, node.col_offset,
                        "%s.%s is written here without a lock, but "
                        "other writes in this class hold self.%s — "
                        "a concurrent reader/writer sees torn state; "
                        "take the same lock (or document single-thread "
                        "ownership and suppress)"
                        % (cls, a, "/self.".join(guarded))))
    return out


# ---------------------------------------------------------------------------
# JG011 — thread started without join/daemon ownership, or handed
# shared mutable module state
# ---------------------------------------------------------------------------

def _is_thread_factory(m, call):
    if not isinstance(call, ast.Call):
        return False
    f = call.func
    if not isinstance(f, ast.Attribute) or \
            f.attr not in ("Thread", "thread"):
        return False
    return _resolves_to_module(m, f, ("threading", "mxnet_tpu.sanitizer"))


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _module_mutables(m):
    """Module-level names bound to mutable literals (shared state)."""
    muts = set()
    for n in m.tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            v = n.value
            if isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("list", "dict", "set",
                                      "bytearray")):
                muts.add(n.targets[0].id)
    return muts


def _jg011_thread_binding(m, fi, call):
    """The name the Thread(...) result is bound to in *fi* — a plain
    name ('t'), a 'self.<attr>' string, or None (unbound/indirect)."""
    for n in body_walk(fi.node):
        if isinstance(n, ast.Assign) and n.value is call and \
                len(n.targets) == 1:
            t = n.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            a = _self_attr(t)
            if a is not None:
                return "self." + a
    return None


def check_jg011(project):
    out = []
    for m in project.modules:
        muts = None
        for fi in m.functions:
            for n in body_walk(fi.node):
                if not (isinstance(n, ast.Call)
                        and _is_thread_factory(m, n)):
                    continue
                # (a) ownership: daemon=True at creation, or a join()/
                #     daemon=True ON THE BOUND NAME in the same scope
                #     (or class, for self.<x> = Thread(...)).  The
                #     match is anchored to the variable — a stray
                #     os.path.join/str.join must not count as
                #     ownership.
                d = _kw(n, "daemon")
                daemonized = isinstance(d, ast.Constant) and \
                    d.value is True
                if not daemonized:
                    bound = _jg011_thread_binding(m, fi, n)
                    owned = False
                    if bound is not None:
                        scope_fis = [fi]
                        if bound.startswith("self.") and fi.class_name:
                            scope_fis = list(m.classes.get(
                                fi.class_name, {}).values())
                        pat = re.compile(
                            r"(?<![\w.])%s\s*\.\s*"
                            r"(join\s*\(|daemon\s*=\s*True)"
                            % re.escape(bound))
                        for sfi in scope_fis:
                            seg = "\n".join(m.lines[
                                sfi.node.lineno - 1:
                                getattr(sfi.node, "end_lineno",
                                        sfi.node.lineno)])
                            if pat.search(seg):
                                owned = True
                                break
                    if not owned:
                        out.append(Finding(
                            "JG011", m.relpath, n.lineno, n.col_offset,
                            "thread created in '%s' is neither daemon "
                            "nor joined in this scope — it outlives "
                            "its owner, keeps the process alive at "
                            "exit, and its failures are silently "
                            "dropped; pass daemon=True or own the "
                            "join" % fi.qualname))
                # (b) shared mutable module state passed as args
                args_kw = _kw(n, "args")
                if isinstance(args_kw, (ast.Tuple, ast.List)):
                    if muts is None:
                        muts = _module_mutables(m)
                    for el in args_kw.elts:
                        if isinstance(el, ast.Name) and el.id in muts:
                            out.append(Finding(
                                "JG011", m.relpath, el.lineno,
                                el.col_offset,
                                "thread target receives module-level "
                                "mutable '%s' — shared default state "
                                "mutated off-thread with no lock; "
                                "pass a copy or guard it" % el.id))
    return out


# ---------------------------------------------------------------------------
# JG012 — wall-clock deadline hazard: time.time() feeding an
# elapsed/deadline comparison
# ---------------------------------------------------------------------------

def _is_walltime(m, call):
    """A bare ``time.time()`` call (alias-resolved; no-arg only —
    ``time.monotonic``/``perf_counter`` never match)."""
    if not isinstance(call, ast.Call) or call.args or call.keywords:
        return False
    d = dotted_name(call.func)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    return tail == "time" and m.imports.get(head) == "time"


def check_jg012(project):
    """``time.time()`` used to compute a timeout/deadline that is then
    compared against elapsed time: an NTP step (or leap smear) moves
    the wall clock and the watchdog/timeout fires years early or never
    — heartbeat eviction and hang detection die to exactly this.  Wall
    time is for TIMESTAMPS (log fields, tokens); durations and
    deadlines belong on ``time.monotonic()``.  Flagged: a comparison
    whose operand contains ``time.time()`` (directly or through a
    name assigned from it / from ``time.time() ± x``)."""
    out = []
    for m in project.modules:
        # cheap source prefilter: wall time is always an attribute
        # call, so a module whose text never says ".time(" has nothing
        # to scan (the AST walk below is the expensive part)
        if not any(".time(" in line for line in m.lines):
            continue
        for fi in m.functions:
            nodes = list(body_walk(fi.node))
            if not any(_is_walltime(m, n) for n in nodes):
                continue
            tainted = set()     # names holding wall stamps/deadlines
            for n in nodes:
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name):
                    v = n.value
                    direct = _is_walltime(m, v)
                    arith = (isinstance(v, ast.BinOp)
                             and isinstance(v.op, (ast.Add, ast.Sub))
                             and any(_is_walltime(m, c)
                                     for c in ast.walk(v)))
                    if direct or arith:
                        tainted.add(n.targets[0].id)

            def _op_tainted(op):
                for c in ast.walk(op):
                    if _is_walltime(m, c):
                        return True
                    if isinstance(c, ast.Name) and \
                            isinstance(c.ctx, ast.Load) and \
                            c.id in tainted:
                        return True
                return False

            for n in nodes:
                if isinstance(n, ast.Compare) and (
                        _op_tainted(n.left) or
                        any(_op_tainted(c) for c in n.comparators)):
                    out.append(_f(
                        "JG012", fi, n,
                        "wall-clock deadline in '%s': time.time() "
                        "feeds an elapsed/deadline comparison — an NTP "
                        "step breaks it; use time.monotonic() for "
                        "durations (wall time is for timestamps only)"
                        % fi.qualname))
    return out


# ---------------------------------------------------------------------------
# JG013 — blocking host sync inside a step-dispatch loop
# ---------------------------------------------------------------------------

#: attribute calls that dispatch a train/predict step (the loop bodies
#: whose throughput the async dispatch pipeline protects)
_JG013_STEP_CALLS = {
    "forward_backward_update", "forward_backward", "fit_batch",
    "evaluate_batch", "predict_batch", "train_step",
}
#: attribute calls that block the host on the device (a per-step sync
#: serializes the loop: step N+1 cannot dispatch until N drains)
_JG013_SYNC_CALLS = {
    "asnumpy", "asscalar", "item", "tolist", "block_until_ready",
    "wait_to_read", "waitall",
}


def check_jg013(project):
    """A loop that dispatches train/predict steps AND blocks on a
    device→host sync every iteration: jax dispatch is async, so the
    loop's steady-state throughput should be the device step time —
    one ``.asnumpy()``/``.item()``/``.block_until_ready()`` per
    iteration re-serializes it to (host work + device step) per step
    (the PR-3 guard readback was exactly this; see
    docs/perf_input_pipeline.md).  Move the sync out of the loop
    (read back once at the end), batch it with a bounded lag (the
    ``MXNET_GUARD_READBACK_LAG`` pattern), or suppress with a comment
    when the readback IS the point (metrics flush, debugging)."""
    out = []
    seen = set()
    for m in project.modules:
        for loop in _jg013_loops(m.tree):
            body_calls = [n for n in _jg013_loop_body_walk(loop)
                          if isinstance(n, ast.Call)
                          and isinstance(n.func, ast.Attribute)]
            dispatches = [c for c in body_calls
                          if c.func.attr in _JG013_STEP_CALLS]
            if not dispatches:
                continue
            for c in body_calls:
                if c.func.attr not in _JG013_SYNC_CALLS:
                    continue
                key = (m.relpath, c.lineno, c.col_offset)
                if key in seen:
                    continue   # nested loops: report each sync once
                seen.add(key)
                out.append(Finding(
                    "JG013", m.relpath, c.lineno, c.col_offset,
                    ".%s() blocks the host inside a loop that "
                    "dispatches steps (.%s() at line %d): every "
                    "iteration now waits for the device to drain, so "
                    "step N+1 cannot overlap step N — hoist the sync "
                    "out of the loop or give it a bounded lag (the "
                    "MXNET_GUARD_READBACK_LAG pattern, "
                    "docs/perf_input_pipeline.md)"
                    % (c.func.attr, dispatches[0].func.attr,
                       dispatches[0].lineno)))
    return out


def _jg013_loops(tree):
    """Every for/while node in *tree* (nested defs included — a loop
    is a loop wherever it lives)."""
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.For, ast.While))]


def _jg013_loop_body_walk(loop):
    """Walk a loop's body stopping at nested function/class defs: a
    def inside the loop runs when CALLED, not per iteration, so its
    syncs are not this loop's per-step syncs."""
    stack = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# JG014 — un-audited AOT program build (lower().compile() off-path)
# ---------------------------------------------------------------------------

#: modules allowed to build AOT programs directly: their build sites
#: carry the MXNET_IR_AUDIT hooks that register every program with
#: the graftir auditor/manifest (tools/graftir, docs/ir_audit.md)
_JG014_ALLOWED = {
    "mxnet_tpu/serve/predictor.py",
    "mxnet_tpu/serve/decode.py",
}


def check_jg014(project):
    """A direct ``jit(...).lower(...).compile()`` call site outside
    the audited producers builds an AOT program that bypasses the
    graftir manifest: it ships with no donation/dtype/cost audit and
    CI cannot see it grow.  Route new program families through the
    audited helpers (CompiledPredictor / DecodeEngine /
    Executor.init_fused_step) or add an ``iraudit.audit()`` hook at
    the build site and extend the allowlist."""
    out = []
    for m in project.modules:
        if m.relpath.replace("\\", "/") in _JG014_ALLOWED:
            continue
        # names assigned from a .lower(...) call (the split form:
        # lowered = jit.lower(...); ...; lowered.compile())
        lowered_names = set()
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "lower":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lowered_names.add(t.id)
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compile"):
                continue
            v = node.func.value
            chained = (isinstance(v, ast.Call)
                       and isinstance(v.func, ast.Attribute)
                       and v.func.attr == "lower")
            via_var = isinstance(v, ast.Name) and v.id in lowered_names
            if chained or via_var:
                out.append(Finding(
                    "JG014", m.relpath, node.lineno, node.col_offset,
                    "AOT program compiled outside the audited "
                    "producers (.lower(...).compile()): it bypasses "
                    "the graftir manifest/audit — build it through "
                    "CompiledPredictor/DecodeEngine/init_fused_step, "
                    "or add an iraudit.audit() hook and extend the "
                    "JG014 allowlist (docs/ir_audit.md)"))
    return out


# ---------------------------------------------------------------------------
# JG015 — condition wait() guarded by `if` instead of `while`
# ---------------------------------------------------------------------------


def check_jg015(project):
    """``with cond: if not pred: cond.wait()`` loses wakeups: a
    spurious wakeup, a stolen wakeup (another waiter consumed the
    state between notify and this thread's re-acquire) or a notify
    that raced ahead of the wait leaves the thread running with the
    predicate still false.  The condition-variable contract is a
    re-checked loop — ``while not pred: cond.wait()`` — or
    ``cond.wait_for(pred)``, which loops internally.  Flagged: a
    ``.wait(...)`` on the object named in the enclosing ``with``
    whose nearest guard is an ``if`` with no loop between them
    (a wait inside any while/for re-check loop is fine)."""
    out = []

    def scan(m, body, conds, in_if, in_loop):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.While, ast.For)):
                scan(m, stmt.body, conds, False, True)
                scan(m, stmt.orelse, conds, in_if, in_loop)
                continue
            if isinstance(stmt, ast.If):
                scan(m, stmt.body, conds, True, in_loop)
                scan(m, stmt.orelse, conds, True, in_loop)
                continue
            if isinstance(stmt, ast.With):
                inner = conds | {dotted_name(i.context_expr)
                                 for i in stmt.items} - {None}
                scan(m, stmt.body, inner, in_if, in_loop)
                continue
            if isinstance(stmt, ast.Try):
                scan(m, stmt.body, conds, in_if, in_loop)
                scan(m, stmt.orelse, conds, in_if, in_loop)
                scan(m, stmt.finalbody, conds, in_if, in_loop)
                for h in stmt.handlers:
                    scan(m, h.body, conds, in_if, in_loop)
                continue
            if not (in_if and not in_loop):
                continue
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "wait" and \
                        dotted_name(n.func.value) in conds:
                    out.append(_f(
                        "JG015", m, n,
                        "condition wait() guarded by 'if' instead of "
                        "'while': a spurious or stolen wakeup resumes "
                        "with the predicate still false (lost "
                        "wakeup) — re-check in a loop ('while not "
                        "pred: cond.wait()') or use "
                        "cond.wait_for(pred)"))

    for m in project.modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.With):
                continue
            conds = {dotted_name(i.context_expr)
                     for i in node.items} - {None}
            if conds:
                scan(m, node.body, conds, False, False)
    return out


# ---------------------------------------------------------------------------

ALL_RULES = {
    "JG001": check_jg001,
    "JG002": check_jg002,
    "JG003": check_jg003,
    "JG004": check_jg004,
    "JG005": check_jg005,
    "JG006": check_jg006,
    "JG007": check_jg007,
    "JG008": check_jg008,
    "JG009": check_jg009,
    "JG010": check_jg010,
    "JG011": check_jg011,
    "JG012": check_jg012,
    "JG013": check_jg013,
    "JG014": check_jg014,
    "JG015": check_jg015,
}

RULE_DOCS = {
    "JG001": "host materialization of possibly-traced values "
             "(float()/int()/bool()/.item()/.tolist()/np.asarray on "
             "values reachable from a jax.jit or register_op trace)",
    "JG002": "use of a buffer after it was donated to a "
             "donate_argnums jit call in the same scope",
    "JG003": "side effects under trace: print/profiler/logging calls "
             "and global/nonlocal writes in jit-reachable code run "
             "once at trace time, then silently never again",
    "JG004": "recompile hazards: time/random/datetime under trace, "
             "jax.jit built inside a loop, unhashable static args",
    "JG005": "register_op contract: donate indices must address real "
             "array inputs, num_outputs must match the statically "
             "visible return arity, needs_rng ops must accept a key",
    "JG006": "silent overbroad except (bare/Exception) in dispatch-path "
             "modules swallows jax.errors",
    "JG007": "mutable default argument shared across calls in API "
             "functions",
    "JG008": "jnp/jax backend-forcing call at module import time",
    "JG009": "non-atomic persistence write: open()-for-write/np.save*/"
             "pickle.dump of a checkpoint or optimizer-state path not "
             "routed through resilience.checkpoint.atomic_write",
    "JG010": "shared attribute written both with and without the lock "
             "that guards it elsewhere in the class — torn state under "
             "concurrency (static companion of the graftsan lockset "
             "race detector)",
    "JG011": "thread started without join/daemon ownership, or handed "
             "module-level mutable state through args (static "
             "companion of the graftsan thread registry)",
    "JG012": "wall-clock deadline hazard: time.time() used to compute "
             "a timeout/deadline compared against elapsed time (NTP "
             "steps break watchdogs; use time.monotonic())",
    "JG013": "blocking host sync (.asnumpy()/.item()/"
             ".block_until_ready()/...) inside a loop that dispatches "
             "train/predict steps — re-serializes the async dispatch "
             "pipeline to host+device per step; hoist the sync or "
             "bound its lag",
    "JG014": "AOT program built off-path: .lower(...).compile() "
             "outside the audited producers bypasses the graftir "
             "manifest/audit (tools/graftir; route through "
             "CompiledPredictor/DecodeEngine or hook iraudit.audit)",
    "JG015": "condition wait() guarded by 'if' instead of 'while' — "
             "a spurious or stolen wakeup resumes with the predicate "
             "still false (lost wakeup); re-check in a loop or use "
             "wait_for (static companion of the graftsched "
             "schedule explorer)",
}
