"""graftlint — AST-level JAX-hazard analyzer for the mxnet_tpu tree.

The reference framework caught operator misuse at compile time through
nnvm attribute checks and the dmlc type registries; the JAX rebuild has
no compiler front-end of its own, so the hazard classes the fused train
step introduced (donated-buffer reuse, host round-trips under trace,
silent per-step recompiles) are only visible at runtime — if at all.
graftlint restores an ahead-of-time whole-program check (Relay's
argument, applied as a linter): a pure-stdlib ``ast`` pass, a call
graph seeded from ``register_op`` registrations and ``jax.jit`` sites,
and a rule engine with per-line suppressions and a committed baseline.

Usage::

    python -m tools.graftlint mxnet_tpu            # lint, exit 1 on new findings
    python -m tools.graftlint mxnet_tpu --format json
    python -m tools.graftlint mxnet_tpu --update-baseline

The analyzer never imports the code it checks (no jax, no mxnet_tpu
import) — it is safe on a machine with no accelerator stack and fast
enough for the tier-1 sanity stage.

Rules
-----
JG001  host materialization of possibly-traced values
JG002  use of a donated buffer after the donating call
JG003  side effects under trace (fire once at trace time, then vanish)
JG004  recompile hazards (time/random under trace, jit-in-loop, ...)
JG005  register_op contract violations (donate/num_outputs/needs_rng)
JG006  silent overbroad exception handler in a dispatch path
JG007  mutable default argument in public API
JG008  jnp/jax backend-forcing call at module import time
JG009  non-atomic persistence write (bypasses atomic_write)
JG010  attribute written both with and without its guarding lock
JG011  thread without join/daemon ownership or with shared mutable args

JG010/JG011 are the static companions of the graftsan runtime
sanitizer suite (tools/graftsan, docs/sanitizers.md).

Suppress a single line with ``# graftlint: disable=JG003`` (comma-
separate multiple IDs, or ``disable=all``).
"""

from .engine import LintEngine, Finding, Baseline  # noqa: F401
from .rules import ALL_RULES, RULE_DOCS  # noqa: F401

__version__ = "1.0"
