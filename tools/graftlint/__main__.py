"""CLI for graftlint: ``python -m tools.graftlint [paths...]``.

Exit codes: 0 = clean (every finding baselined or suppressed),
1 = un-baselined findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import sys

from .engine import DEFAULT_BASELINE, LintEngine
from .rules import ALL_RULES, RULE_DOCS


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-level JAX-hazard analyzer for the mxnet_tpu "
                    "tree (tracer leaks, donation misuse, recompile "
                    "hazards). Never imports the code it checks.",
        epilog="Baseline workflow: the committed baseline "
               "(tools/graftlint/baseline.json) holds accepted "
               "pre-existing findings; CI fails only on NEW findings. "
               "After fixing old ones, shrink the ledger with "
               "--update-baseline and commit the result. Suppress a "
               "single line with '# graftlint: disable=JG003' "
               "(comma-separated ids, or 'all'). Full rule catalog: "
               "docs/static_analysis.md.")
    p.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                   help="files/directories to analyze "
                        "(default: mxnet_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (default: text)")
    p.add_argument("--rules", metavar="JG001,JG002,...",
                   help="comma-separated subset of rules to run")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   metavar="PATH",
                   help="baseline file (default: %(default)s)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding as new")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0 (commit the result)")
    p.add_argument("--show-all", action="store_true",
                   help="also print baselined/suppressed findings "
                        "(tagged) in text output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULE_DOCS):
            print("%s  %s" % (rid, RULE_DOCS[rid]))
        return 0

    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",")
                 if r.strip()]
        unknown = [r for r in rules if r not in ALL_RULES]
        if unknown:
            print("graftlint: unknown rule(s): %s (have: %s)"
                  % (", ".join(unknown), ", ".join(sorted(ALL_RULES))),
                  file=sys.stderr)
            return 2
    else:
        rules = None

    engine = LintEngine(args.paths, rules=rules,
                        baseline_path=args.baseline,
                        use_baseline=not args.no_baseline)
    try:
        findings = engine.run()
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.update_baseline:
        n = engine.update_baseline(findings)
        print("graftlint: baseline updated (%d finding(s) accepted) -> %s"
              % (n, args.baseline))
        print(engine.summary_line())
        return 0

    if args.format == "json":
        print(engine.report_json(findings))
    else:
        text = engine.report_text(findings, show_all=args.show_all)
        if text:
            print(text)
    # one-line scrapeable summary, always last on stdout (the bench
    # harness greps '^graftlint: ')
    print(engine.summary_line())
    return 1 if engine.stats["new"] else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout went away (e.g. piped into head) mid-report: the run
        # is incomplete, so never report clean — 141 = 128 + SIGPIPE
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(141)
