#!/usr/bin/env python
"""Inference throughput sweep across the model zoo — the TPU mirror of
the reference's `example/image-classification/benchmark_score.py`
(the harness behind every inference table in docs/faq/perf.md:42-175).

One JSON line per (model, batch) with img/s, using bench.py's timing
discipline: batches scanned inside one dispatch, completion forced by a
host readback (``block_until_ready`` does not wait over the tunnel).

    PYTHONPATH=/root/repo:/root/.axon_site python tools/benchmark_score.py \
        [--models resnet50_v1 vgg16 ...] [--batches 1 32 128] [--image 224]

Run only with a healthy tunnel and NO other TPU process.  On CPU
(JAX_PLATFORMS=cpu) shrinks shapes for a plumbing smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the reference sweeps these six families (docs/faq/perf.md tables)
DEFAULT_MODELS = [
    "alexnet", "vgg16", "inception_v3", "resnet50_v1", "resnet152_v1",
    "mobilenet1_0",
]


def _model_image(model, image):
    # inception's canonical input is 299², but only when measuring at
    # full scale — a tiny-shape plumbing smoke stays tiny
    return 299 if model.startswith("inception") and image >= 224 else image


def _trace_and_split(model, batch, image):
    """Build + materialize a zoo model, trace it to a symbol on
    var('data0'), and split its parameters into (arg, aux) NDArray
    dicts.  Shared by the fp and int8 paths."""
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu import nd
    import mxnet_tpu.symbol as sym_mod

    net = vision.get_model(model, classes=1000)
    net.initialize()
    net.hybridize()  # one dispatch to materialize, not one per op
    rng = np.random.RandomState(0)
    size = _model_image(model, image)
    x = nd.array(rng.randn(batch, 3, size, size).astype(np.float32))
    net(x)  # materialize params

    out_sym = net(sym_mod.var("data0"))
    if not isinstance(out_sym, sym_mod.Symbol):
        out_sym = out_sym[0]
    arg_names = set(out_sym.list_arguments())
    aux_names = set(out_sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for p in net.collect_params().values():
        if p.name in arg_names:
            arg_params[p.name] = p.data()
        elif p.name in aux_names:
            aux_params[p.name] = p.data()
    return out_sym, arg_params, aux_params, x


def timed_infer(model, batch, image, iters=40, scan_n=10, warmup=2,
                dtype="bfloat16"):
    import jax.numpy as jnp
    from mxnet_tpu.executor import _build_eval
    import bench

    out_sym, arg_params, aux_params, x = _trace_and_split(
        model, batch, image)
    eval_fn = _build_eval(out_sym, False)
    cdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    params = {k: v._data.astype(cdt) for k, v in arg_params.items()}
    aux = {k: v._data for k, v in aux_params.items()}
    xd = x._data.astype(cdt)

    dt, n, _ = bench.timed_scan_forward(eval_fn, params, aux, xd, {},
                                        scan_n, iters, warmup)
    return batch * n / dt


def timed_infer_int8(model, batch, image, iters=40, scan_n=10,
                     warmup=2):
    """INT8 inference via the quantization graph rewrite
    (contrib.quantization.quantize_model, naive calibration on a
    synthetic batch) — the reference's quantization benchmark path
    (benchmark/python/quantization)."""
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.executor import _build_eval
    import bench

    out_sym, arg_params, aux_params, x = _trace_and_split(
        model, batch, image)
    calib = NDArrayIter(np.asarray(x.asnumpy()), None,
                        batch_size=batch)
    qsym, qargs, qaux = quantize_model(
        out_sym, arg_params, aux_params, data_names=("data0",),
        calib_mode="naive", calib_data=calib,
        num_calib_examples=batch)

    eval_fn = _build_eval(qsym, False)
    params = {k: v._data for k, v in qargs.items()}
    aux = {k: v._data for k, v in qaux.items()}
    dt, n, _ = bench.timed_scan_forward(eval_fn, params, aux, x._data,
                                        {}, scan_n, iters, warmup)
    return batch * n / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", nargs="*", default=DEFAULT_MODELS)
    ap.add_argument("--batches", nargs="*", type=int,
                    default=[1, 32, 128])
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32", "int8"])
    ap.add_argument("--iters", type=int, default=40)
    args = ap.parse_args()

    # mxnet_tpu re-pins jax_platforms from the env var — the axon site
    # hook force-sets 'axon,cpu' at startup, so a bare jax.devices()
    # would initialize (and hang on) the tunnel even under
    # JAX_PLATFORMS=cpu
    import mxnet_tpu  # noqa: F401
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        # plumbing smoke only: small shapes (64 is the smallest every
        # default family accepts — alexnet's 11x11/s4 stack collapses
        # below that), tiny batches
        args.image, args.batches = 64, [2]
        args.iters = 4

    for model in args.models:
        for batch in args.batches:
            try:
                if args.dtype == "int8":
                    img_s = timed_infer_int8(model, batch, args.image,
                                             iters=args.iters)
                else:
                    img_s = timed_infer(model, batch, args.image,
                                        iters=args.iters,
                                        dtype=args.dtype)
                print(json.dumps({
                    "model": model, "batch": batch,
                    "dtype": args.dtype,
                    "image": _model_image(model, args.image),
                    "img_s": round(img_s, 2),
                    "device": ("tpu" if on_tpu else "cpu"),
                }), flush=True)
            except Exception as e:
                print(json.dumps({"model": model, "batch": batch,
                                  "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
