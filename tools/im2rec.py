#!/usr/bin/env python
"""im2rec — pack an image folder / .lst file into RecordIO
(reference capability: tools/im2rec.py + im2rec.cc).

Usage:
  python tools/im2rec.py PREFIX ROOT --list        # write PREFIX.lst
  python tools/im2rec.py PREFIX ROOT               # pack PREFIX.rec/.idx
"""

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

EXTS = (".jpg", ".jpeg", ".png")


def make_list(prefix, root, shuffle=True):
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)))
    entries = []
    if classes:
        for label, cls in enumerate(classes):
            for fn in sorted(os.listdir(os.path.join(root, cls))):
                if fn.lower().endswith(EXTS):
                    entries.append((os.path.join(cls, fn), float(label)))
    else:
        for i, fn in enumerate(sorted(os.listdir(root))):
            if fn.lower().endswith(EXTS):
                entries.append((fn, 0.0))
    if shuffle:
        random.shuffle(entries)
    with open(prefix + ".lst", "w") as f:
        for i, (path, label) in enumerate(entries):
            f.write("%d\t%f\t%s\n" % (i, label, path))
    return prefix + ".lst"


def make_record(prefix, root, resize=0, quality=95, color=1):
    from mxnet_tpu import recordio
    from mxnet_tpu.image import imread, resize_short

    lst = prefix + ".lst"
    if not os.path.exists(lst):
        make_list(prefix, root)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    with open(lst) as f:
        for line in f:
            idx_s, label_s, path = line.strip().split("\t")
            img = imread(os.path.join(root, path), flag=color)
            if resize:
                img = resize_short(img, resize)
            header = recordio.IRHeader(0, float(label_s), int(idx_s), 0)
            rec.write_idx(int(idx_s),
                          recordio.pack_img(header, img,
                                            quality=quality))
    rec.close()
    return prefix + ".rec"


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true",
                   help="only generate the .lst file")
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--no-shuffle", action="store_true")
    args = p.parse_args()
    if args.list:
        print(make_list(args.prefix, args.root,
                        shuffle=not args.no_shuffle))
    else:
        print(make_record(args.prefix, args.root, resize=args.resize,
                          quality=args.quality))


if __name__ == "__main__":
    main()
