"""graftir engine: run the rules over a program set, filter through
per-program suppressions and the committed baseline, report.

Mirrors graftlint's engine shape (Finding / Baseline / engine.run()
/ summary line / JSON report) so the two analyzers read the same in
CI, but the unit of audit is a lowered *program*, not a source file:
suppressions are declared by the producer at registration
(``Program(..., suppress=("GI004",))``) instead of line comments, and
baseline fingerprints key on (rule, program key, detail) — stable
across HLO line-number drift.
"""

from __future__ import annotations

import json
import os
import time

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
BASELINE_VERSION = 1


class Finding:
    __slots__ = ("rule", "program", "line", "message", "detail", "status")

    def __init__(self, rule, program, message, line=0, detail=""):
        self.rule = rule
        self.program = program          # the Program record
        self.line = line                # line in the HLO text (0 = n/a)
        self.message = message
        self.detail = detail            # stable fingerprint component
        self.status = "new"             # new | baselined | suppressed

    def fingerprint(self):
        return "%s|%s|%s" % (self.rule, self.program.key(), self.detail)

    def as_dict(self):
        return {"rule": self.rule, "program": self.program.key(),
                "line": self.line, "message": self.message,
                "status": self.status}

    def __repr__(self):
        where = self.program.key()
        if self.line:
            where += ":%d" % self.line
        return "%s: %s %s" % (where, self.rule, self.message)


class Baseline:
    """Committed ledger of accepted pre-existing findings."""

    def __init__(self, path=DEFAULT_BASELINE):
        self.path = path
        self.counts = {}

    @classmethod
    def load(cls, path=DEFAULT_BASELINE):
        b = cls(path)
        if path and os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
            b.counts = dict(data.get("findings", {}))
        return b

    def save(self, findings):
        entries = {}
        for f in findings:
            fp = f.fingerprint()
            entries[fp] = entries.get(fp, 0) + 1
        payload = {
            "version": BASELINE_VERSION,
            "comment": "accepted pre-existing graftir findings; "
                       "regenerate with --update-baseline (see "
                       "docs/ir_audit.md)",
            "findings": dict(sorted(entries.items())),
        }
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")

    def consume(self, finding):
        fp = finding.fingerprint()
        left = self.counts.get(fp, 0)
        if left > 0:
            self.counts[fp] = left - 1
            return True
        return False


class AuditEngine:
    """Run rule checks over an audited program list."""

    def __init__(self, programs, rules=None,
                 baseline_path=DEFAULT_BASELINE, use_baseline=True):
        from .rules import ALL_RULES
        self.programs = list(programs)
        self.rule_ids = sorted(rules or ALL_RULES)
        self.rules = {rid: ALL_RULES[rid] for rid in self.rule_ids}
        self.baseline_path = baseline_path
        self.use_baseline = use_baseline
        self.stats = {}

    def run(self):
        t0 = time.perf_counter()
        findings = []
        for rid in self.rule_ids:
            findings.extend(self.rules[rid](self.programs))
        findings.sort(key=lambda f: (f.program.key(), f.rule, f.line))

        baseline = Baseline.load(self.baseline_path) \
            if self.use_baseline else Baseline(self.baseline_path)
        n_sup = n_base = 0
        for f in findings:
            if f.rule in f.program.suppress:
                f.status = "suppressed"
                n_sup += 1
            elif baseline.consume(f):
                f.status = "baselined"
                n_base += 1

        new = [f for f in findings if f.status == "new"]
        self.stats = {
            "programs": len(self.programs),
            "rules": len(self.rule_ids),
            "findings": len(findings),
            "suppressed": n_sup,
            "baselined": n_base,
            "new": len(new),
            "seconds": round(time.perf_counter() - t0, 3),
        }
        return findings

    def update_baseline(self, findings):
        keep = [f for f in findings if f.status != "suppressed"]
        Baseline(self.baseline_path).save(keep)
        return len(keep)

    # -- reporting --------------------------------------------------------

    def summary_line(self):
        s = self.stats
        return ("graftir: programs=%d rules=%d findings=%d baselined=%d "
                "suppressed=%d new=%d time=%.2fs"
                % (s["programs"], s["rules"], s["findings"],
                   s["baselined"], s["suppressed"], s["new"],
                   s["seconds"]))

    def report_text(self, findings, show_all=False):
        out = []
        for f in findings:
            if f.status == "new" or show_all:
                tag = "" if f.status == "new" else " [%s]" % f.status
                where = f.program.key()
                if f.line:
                    where += ":%d" % f.line
                out.append("%s: %s%s %s" % (where, f.rule, tag, f.message))
        return "\n".join(out)

    def report_json(self, findings):
        return json.dumps({"summary": self.stats,
                           "findings": [f.as_dict() for f in findings]},
                          indent=1)


def audit_programs(programs, **kw):
    """One-call audit (bridge/test entry): (engine, findings)."""
    eng = AuditEngine(programs, **kw)
    return eng, eng.run()
