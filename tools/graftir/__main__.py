"""CLI for graftir: ``python -m tools.graftir [--check]``.

Lowers the representative AOT program set (CPU avals, the audited
programs are never executed), runs rules GI001-GI005 against the
committed baseline, and with ``--check`` also diffs per-program
cost/structure against the committed manifest.

Exit codes: 0 = clean, 1 = new findings or manifest violations,
2 = usage/build error.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser():
    from .engine import DEFAULT_BASELINE
    from .manifest import DEFAULT_MANIFEST
    p = argparse.ArgumentParser(
        prog="python -m tools.graftir",
        description="Static auditor for the framework's lowered "
                    "StableHLO programs (donation coverage, dtype "
                    "policy, host round-trips, pad-waste, program "
                    "budgets) plus a committed per-program cost "
                    "manifest.",
        epilog="Manifest workflow: --check fails on >10%% flops/bytes "
               "growth, program-count drift, or rule regressions; "
               "after an INTENDED change, regenerate with "
               "--update-manifest and commit the diff — the manifest "
               "diff is the review surface. Full rule catalog: "
               "docs/ir_audit.md.")
    p.add_argument("--check", action="store_true",
                   help="also diff the lowered set against the "
                        "committed manifest (CI mode)")
    p.add_argument("--update-manifest", action="store_true",
                   help="rewrite the manifest from the current tree's "
                        "lowered programs and exit 0 (commit the "
                        "result)")
    p.add_argument("--manifest", default=DEFAULT_MANIFEST,
                   metavar="PATH",
                   help="manifest file (default: %(default)s)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (default: text)")
    p.add_argument("--rules", metavar="GI001,GI002,...",
                   help="comma-separated subset of rules to run")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   metavar="PATH",
                   help="baseline file (default: %(default)s)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding as "
                        "new")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0 (commit the result)")
    p.add_argument("--show-all", action="store_true",
                   help="also print baselined/suppressed findings "
                        "(tagged) in text output")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from .rules import ALL_RULES, RULE_DOCS
    if args.list_rules:
        for rid in sorted(RULE_DOCS):
            print("%s  %s" % (rid, RULE_DOCS[rid]))
        return 0

    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",")
                 if r.strip()]
        unknown = [r for r in rules if r not in ALL_RULES]
        if unknown:
            print("graftir: unknown rule(s): %s (have: %s)"
                  % (", ".join(unknown), ", ".join(sorted(ALL_RULES))),
                  file=sys.stderr)
            return 2
    else:
        rules = None

    # the representative set lowers on CPU avals: pin the platform
    # BEFORE jax initializes so the committed manifest shas reproduce
    # on any machine
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from . import manifest as _manifest
    from .engine import AuditEngine
    from .programs import build_representative_set
    try:
        programs = build_representative_set()
    except Exception as e:     # a build failure must not read as clean
        print("graftir: representative set failed to lower: %r" % e,
              file=sys.stderr)
        return 2

    engine = AuditEngine(programs, rules=rules,
                         baseline_path=args.baseline,
                         use_baseline=not args.no_baseline)
    findings = engine.run()

    if args.update_baseline:
        n = engine.update_baseline(findings)
        print("graftir: baseline updated (%d finding(s) accepted) -> %s"
              % (n, args.baseline))
        print(engine.summary_line())
        return 0

    if args.update_manifest:
        payload = _manifest.build(programs)
        _manifest.save(payload, args.manifest)
        print("graftir: manifest updated (%d program(s)) -> %s"
              % (len(payload["programs"]), args.manifest))
        print(engine.summary_line())
        return 0

    violations = []
    diff_rows = []
    if args.check:
        if not os.path.exists(args.manifest):
            print("graftir: no manifest at %s — run --update-manifest "
                  "and commit it" % args.manifest, file=sys.stderr)
            return 2
        diff_rows, violations = _manifest.diff(
            programs, _manifest.load(args.manifest))

    if args.format == "json":
        import json
        report = json.loads(engine.report_json(findings))
        report["manifest"] = {"rows": diff_rows,
                              "violations": violations}
        print(json.dumps(report, indent=1))
    else:
        text = engine.report_text(findings, show_all=args.show_all)
        if text:
            print(text)
        if args.check:
            print(_manifest.format_diff_table(diff_rows),
                  file=sys.stderr)
            for v in violations:
                print("graftir: manifest: %s" % v)
    # one-line scrapeable summary, always last on stdout (CI greps
    # '^graftir: ')
    print(engine.summary_line())
    return 1 if (engine.stats["new"] or violations) else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout went away mid-report: the run is incomplete, never
        # report clean — 141 = 128 + SIGPIPE
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(141)
