"""The committed program manifest: per-program cost/structure facts,
and the diff that gates CI.

Every audited program registers (subsystem, name, input avals, sha of
the canonicalized HLO text, flops/bytes and per-op top-k from
``observability.costs``) into ``tools/graftir/manifest.json``.
``python -m tools.graftir --check`` re-lowers the representative set
and diffs it against the committed file; the check fails on

* **program-count drift** — a program appeared or disappeared
  (new rung, forked variant, dropped coverage);
* **cost growth** — a program whose canonical sha changed grew >10%
  in flops or bytes without ``--update-manifest`` being run;
* anything else is reported as drift-within-tolerance and passes.

This is what makes kernel/lowering PRs carry an attributable,
reviewable diff: the manifest change IS the review surface, on CPU,
before any TPU time is spent.
"""

from __future__ import annotations

import json
import os

from .hlo import cost_summary

DEFAULT_MANIFEST = os.path.join(os.path.dirname(__file__),
                                "manifest.json")
MANIFEST_VERSION = 1
GROWTH_TOLERANCE = 0.10          # >10% flops/bytes growth fails


def build(programs, top=5):
    """Manifest payload (dict) for a program list."""
    entries = {}
    for p in programs:
        cost = cost_summary(p.text, top=top)
        entries[p.key()] = {
            "subsystem": p.subsystem,
            "model": p.model,
            "name": p.name,
            "avals": p.avals(),
            "sha": p.sha(),
            "flops": cost["flops"],
            "bytes": cost["bytes"],
            "top_ops": cost["top_ops"],
            "donated": p.donated_args(),
        }
    return {
        "version": MANIFEST_VERSION,
        "comment": "committed per-program cost/structure manifest; "
                   "regenerate with --update-manifest (see "
                   "docs/ir_audit.md)",
        "programs": dict(sorted(entries.items())),
    }


def save(payload, path=DEFAULT_MANIFEST):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def load(path=DEFAULT_MANIFEST):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def diff(programs, manifest, tolerance=GROWTH_TOLERANCE):
    """Compare current programs against a committed manifest.

    Returns ``(rows, violations)``: *rows* is the full per-program
    diff table (``{program, status, flops, bytes, dflops, dbytes}``
    with status ``ok | changed | grew | new | removed``); *violations*
    the subset of human-readable failures."""
    old = manifest.get("programs", {})
    cur = build(programs)["programs"]
    rows, violations = [], []

    for key in sorted(set(old) | set(cur)):
        o, c = old.get(key), cur.get(key)
        if o is None:
            rows.append({"program": key, "status": "new",
                         "flops": c["flops"], "bytes": c["bytes"],
                         "dflops": None, "dbytes": None})
            violations.append(
                "%s: program not in manifest (program-count drift — "
                "run --update-manifest to accept)" % key)
            continue
        if c is None:
            rows.append({"program": key, "status": "removed",
                         "flops": 0.0, "bytes": 0.0,
                         "dflops": None, "dbytes": None})
            violations.append(
                "%s: program in manifest but no longer lowered "
                "(program-count drift — run --update-manifest to "
                "accept)" % key)
            continue
        if c["sha"] == o["sha"]:
            rows.append({"program": key, "status": "ok",
                         "flops": c["flops"], "bytes": c["bytes"],
                         "dflops": 0.0, "dbytes": 0.0})
            continue
        dflops = _rel(o["flops"], c["flops"])
        dbytes = _rel(o["bytes"], c["bytes"])
        grew = dflops > tolerance or dbytes > tolerance
        rows.append({"program": key,
                     "status": "grew" if grew else "changed",
                     "flops": c["flops"], "bytes": c["bytes"],
                     "dflops": dflops, "dbytes": dbytes})
        if grew:
            violations.append(
                "%s: cost grew beyond %.0f%% tolerance "
                "(flops %+.1f%%, bytes %+.1f%%) — investigate, then "
                "--update-manifest if intended"
                % (key, 100 * tolerance, 100 * dflops, 100 * dbytes))
    return rows, violations


def _rel(old, new):
    if old <= 0:
        return 0.0 if new <= 0 else float("inf")
    return (new - old) / old


def format_diff_table(rows):
    """Human diff table (for stderr / bench --audit)."""
    out = ["%-44s %-8s %12s %12s %8s %8s"
           % ("program", "status", "flops", "bytes", "dflops",
              "dbytes")]
    for r in rows:
        def pct(v):
            if v is None:
                return "-"
            if v == float("inf"):
                return "inf"
            return "%+.1f%%" % (100 * v)
        out.append("%-44s %-8s %12.3g %12.3g %8s %8s"
                   % (r["program"], r["status"], r["flops"], r["bytes"],
                      pct(r["dflops"]), pct(r["dbytes"])))
    return "\n".join(out)
