"""HLO-text primitives for graftir: the :class:`Program` record plus
the small parsers the rules share.

graftir audits the *pretty-printed StableHLO text* that
``jax.jit(...).lower(...)`` produces — the same text
``mxnet_tpu.observability.costs`` prices — so everything here is
regex-over-lines, dependency-light, and never executes a program.
"""

from __future__ import annotations

import hashlib
import re

# op lines: "%3 = stablehlo.dot_general ..." (quoted generic form too)
OP_RE = re.compile(r'=\s+"?(?:stablehlo|mhlo|chlo)\.([\w.]+)"?')
TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_MAIN_RE = re.compile(r"func\.func\s+(?:public\s+)?@main\s*\(")
_ARG_RE = re.compile(r"%arg\d+\s*:\s*tensor<([^>]*)>")
_DONATE_ATTR_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")
_LOC_RE = re.compile(r"\s*loc\(.*?\)")

# custom_call targets that mean "leave the device / call the host".
# @Sharding, @cu*, @annotate_device_placement-style markers are benign.
HOST_CALL_RE = re.compile(
    r"callback|host|infeed|outfeed|xla_python|py_func", re.IGNORECASE)


class Program:
    """One audited lowered program plus the producer's declarations.

    The declarations are the contract the rules check the HLO against:

    ``donated``
        number of entry args the producing subsystem declares
        donatable (``None`` = subsystem makes no donation promise).
    ``dtype_policy``
        ``None`` | ``"bf16"`` | ``"int8"`` | ``"int8-weight-only"``.
    ``hot_path``
        True for request/step-path programs where a host round-trip
        is a latency bug (GI003).
    ``bucket_rows`` / ``natural_rows``
        padded batch rows of this bucket rung vs the worst-case
        natural rows routed to it (GI004 pad-waste).
    ``budget``
        expected program count for this (subsystem, model) group
        (GI005); every program in the group should declare the same
        budget.
    ``suppress``
        rule ids accepted for this program (the per-program analogue
        of graftlint's ``# graftlint: disable=`` comments).
    """

    __slots__ = ("subsystem", "model", "name", "text", "donated",
                 "dtype_policy", "hot_path", "bucket_rows",
                 "natural_rows", "budget", "suppress", "f32_allow")

    def __init__(self, subsystem, name, text, model="", donated=None,
                 dtype_policy=None, hot_path=False, bucket_rows=None,
                 natural_rows=None, budget=None, suppress=(),
                 f32_allow=()):
        self.subsystem = subsystem
        self.model = model
        self.name = name
        self.text = text
        self.donated = donated
        self.dtype_policy = dtype_policy
        self.hot_path = hot_path
        self.bucket_rows = bucket_rows
        self.natural_rows = natural_rows
        self.budget = budget
        self.suppress = frozenset(r.upper() for r in suppress)
        self.f32_allow = frozenset(f32_allow)

    # -- derived views ----------------------------------------------------

    def main_args(self):
        """[(aval_str, donated_bool)] for the @main entry signature."""
        m = _MAIN_RE.search(self.text)
        if not m:
            return []
        # consume the balanced-paren arg list (the signature may wrap)
        depth = 1
        i = m.end()
        while i < len(self.text) and depth:
            c = self.text[i]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            i += 1
        sig = self.text[m.end():i - 1]
        # split on %argN boundaries rather than regexing the attr
        # dicts: attrs like mhlo.sharding = "{replicated}" nest braces
        out = []
        for part in re.split(r"(?=%arg\d+\s*:)", sig):
            am = _ARG_RE.match(part.strip())
            if not am:
                continue
            out.append((am.group(1).replace(" ", ""),
                        bool(_DONATE_ATTR_RE.search(part))))
        return out

    def avals(self):
        return [a for a, _ in self.main_args()]

    def donated_args(self):
        return sum(1 for _, d in self.main_args() if d)

    def op_lines(self):
        """[(lineno, opname, line)] for every dialect instruction."""
        out = []
        for i, line in enumerate(self.text.splitlines(), 1):
            m = OP_RE.search(line)
            if m:
                out.append((i, m.group(1), line))
        return out

    def sha(self):
        return canonical_sha(self.text)

    def key(self):
        return "%s/%s" % (self.subsystem, self.name)


def canonicalize(text):
    """Normalize lowered text so the sha is stable across runs:
    location info, the module-attr header, and whitespace drift carry
    no program semantics."""
    lines = []
    for line in text.splitlines():
        if line.lstrip().startswith("#loc"):
            continue
        line = _LOC_RE.sub("", line)
        if line.lstrip().startswith("module @"):
            line = "module"
        line = " ".join(line.split())
        if line:
            lines.append(line)
    return "\n".join(lines)


def canonical_sha(text):
    return hashlib.sha256(
        canonicalize(text).encode("utf-8")).hexdigest()[:16]


def cost_summary(text, top=5):
    """{flops, bytes, top_ops} via observability.costs (loop-aware)."""
    from mxnet_tpu.observability import costs
    rows = costs.parse_hlo_ops(text)
    agg = {}
    for r in rows:
        a = agg.setdefault(r["op"], {"op": r["op"], "flops": 0.0,
                                     "bytes": 0.0})
        a["flops"] += r["flops"]
        a["bytes"] += r["bytes"]
    top_ops = sorted(agg.values(),
                     key=lambda a: (-a["flops"], -a["bytes"], a["op"]))
    return {
        "flops": float(sum(r["flops"] for r in rows)),
        "bytes": float(sum(r["bytes"] for r in rows)),
        "top_ops": [{"op": a["op"], "flops": a["flops"],
                     "bytes": a["bytes"]} for a in top_ops[:top]],
    }
