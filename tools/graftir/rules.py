"""graftir rules GI001-GI005: whole-program properties checked as
facts on the lowered StableHLO text.

Every rule is ``check(programs) -> [Finding]`` over the full audited
program list (GI005 needs the group view; the others iterate).  Rule
ids, like graftlint's, are stable API: docs, suppressions, and the
baseline key on them.
"""

from __future__ import annotations

import re

from .hlo import HOST_CALL_RE, TENSOR_RE
from .engine import Finding

# ---------------------------------------------------------------------------
# GI001 — donation coverage


def check_gi001(programs):
    """Inputs declared donatable must carry input-output aliasing.

    Generalizes the predictor's ad-hoc ``tf.aliasing_output`` grep to
    every producer: a fused step, decode tick, or quantized rung that
    promises donation but lowers without the attrs re-allocates its
    largest buffers every dispatch."""
    out = []
    for p in programs:
        if p.donated is None:
            continue
        have = p.donated_args()
        if have < p.donated:
            out.append(Finding(
                "GI001", p,
                "declares %d donatable input(s) but only %d carry "
                "tf.aliasing_output/jax.buffer_donor in the lowered "
                "program" % (p.donated, have),
                detail="declared=%d" % p.donated))
    return out


# ---------------------------------------------------------------------------
# GI002 — dtype policy conformance

# (?:\b|x): "4xf64" has no word boundary before the "f"
_F64_RE = re.compile(r"(?:\b|x)f64\b")
_I8_OPERAND_RE = re.compile(r"tensor<[^>]*i8>")
_COMPUTE_OPS = ("dot_general", "dot", "convolution")


def _line_result_dtype(line):
    """Element dtype of the result tensor on an instruction line."""
    tensors = TENSOR_RE.findall(line)
    if not tensors:
        return None
    m = re.search(r"([a-z]+[0-9]+)$", tensors[-1].split("x")[-1].strip())
    return m.group(1) if m else None


def check_gi002(programs):
    """Dtype policy: no f64 anywhere; under the bf16 matmul policy no
    dot/conv computes in f32 unless allowlisted; quantized rungs must
    compute their declared conv/FC ops in i8/i32 (subsumes the
    ``quantize/lower.py`` int8-dot probe)."""
    out = []
    for p in programs:
        for lineno, op, line in p.op_lines():
            if _F64_RE.search(line):
                out.append(Finding(
                    "GI002", p,
                    "f64 in lowered program (op %s, line %d) — the "
                    "framework dtype policy forbids double precision"
                    % (op, lineno), line=lineno, detail="f64:%s" % op))
                break       # one finding per program is enough signal
        if p.dtype_policy == "bf16":
            for lineno, op, line in p.op_lines():
                if op in _COMPUTE_OPS and op not in p.f32_allow \
                        and _line_result_dtype(line) == "f32":
                    out.append(Finding(
                        "GI002", p,
                        "%s computes in f32 at line %d under the bf16 "
                        "matmul policy (allowlist via f32_allow or a "
                        "GI002 suppression if intended)" % (op, lineno),
                        line=lineno, detail="f32:%s" % op))
                    break
        elif p.dtype_policy in ("int8", "int8-weight-only"):
            compute = [ln for _, op, ln in p.op_lines()
                       if op in _COMPUTE_OPS]
            if p.dtype_policy == "int8":
                ok = any(_I8_OPERAND_RE.search(ln) for ln in compute)
                what = "no dot/conv computes on i8 operands"
            else:
                ok = bool(_I8_OPERAND_RE.search(p.text))
                what = "no i8 tensors present"
            if compute and not ok:
                out.append(Finding(
                    "GI002", p,
                    "declared %s rung but %s — quantization was lost "
                    "in lowering" % (p.dtype_policy, what),
                    detail="lost-int8"))
    return out


# ---------------------------------------------------------------------------
# GI003 — host round-trips in hot-path programs

_HOST_OPS = frozenset(["infeed", "outfeed", "send", "recv",
                       "host_compute"])
_TARGET_RE = re.compile(r'custom_call\s+@([\w$.]+)|call_target_name\s*=\s*"([^"]+)"')


def check_gi003(programs):
    """A hot-path program (request path, fused step, decode tick) must
    never round-trip through the host mid-program: infeed/outfeed/
    send/recv, or a custom_call into a python/host callback, turns a
    single dispatch into a latency cliff."""
    out = []
    for p in programs:
        if not p.hot_path:
            continue
        for lineno, op, line in p.op_lines():
            if op in _HOST_OPS:
                out.append(Finding(
                    "GI003", p,
                    "host transfer op %s at line %d in a hot-path "
                    "program" % (op, lineno),
                    line=lineno, detail="op:%s" % op))
            elif op == "custom_call":
                m = _TARGET_RE.search(line)
                target = (m.group(1) or m.group(2)) if m else ""
                if target and HOST_CALL_RE.search(target):
                    out.append(Finding(
                        "GI003", p,
                        "custom_call @%s at line %d calls back into "
                        "the host from a hot-path program"
                        % (target, lineno),
                        line=lineno, detail="cc:%s" % target))
    return out


# ---------------------------------------------------------------------------
# GI004 — pad-waste per bucket rung

PAD_WASTE_THRESHOLD = 0.75


def check_gi004(programs, threshold=PAD_WASTE_THRESHOLD):
    """Share of dot/conv flops attributable to padding rows.

    Bucket rungs trade recompiles for padded work; that trade has a
    budget.  With batch-linear compute, the waste share for a rung
    padded to ``bucket_rows`` whose worst-case natural batch is
    ``natural_rows`` is ``1 - natural/bucket``; above the threshold
    the rung is mis-bucketed (e.g. a (1, 64) ladder sends a 2-row
    request through the 64-row program at 97% waste)."""
    out = []
    for p in programs:
        if not p.bucket_rows or not p.natural_rows:
            continue
        share = 1.0 - float(p.natural_rows) / float(p.bucket_rows)
        if share > threshold:
            out.append(Finding(
                "GI004", p,
                "pad-waste %.0f%% (bucket rows=%d, worst natural "
                "rows=%d) exceeds the %.0f%% budget — add an "
                "intermediate rung" % (100 * share, p.bucket_rows,
                                       p.natural_rows, 100 * threshold),
                detail="rows=%d" % p.bucket_rows))
    return out


# ---------------------------------------------------------------------------
# GI005 — program-count budget per subsystem


def check_gi005(programs):
    """Each (subsystem, model) group declares its expected program
    count; growth means someone added an AOT program (a new rung, a
    forked variant) without updating the budget — exactly the silent
    compile-time/memory creep the manifest exists to catch."""
    groups = {}
    for p in programs:
        groups.setdefault((p.subsystem, p.model), []).append(p)
    out = []
    for (subsystem, model), members in sorted(groups.items()):
        budgets = {m.budget for m in members if m.budget is not None}
        if not budgets:
            continue
        budget = max(budgets)
        if len(members) > budget:
            rep = members[0]
            out.append(Finding(
                "GI005", rep,
                "subsystem %s%s lowered %d programs against a budget "
                "of %d (%s)" % (
                    subsystem, " model=%s" % model if model else "",
                    len(members), budget,
                    ", ".join(sorted(m.name for m in members))),
                detail="group:%s/%s" % (subsystem, model)))
    return out


ALL_RULES = {
    "GI001": check_gi001,
    "GI002": check_gi002,
    "GI003": check_gi003,
    "GI004": check_gi004,
    "GI005": check_gi005,
}

RULE_DOCS = {
    "GI001": "donation coverage: declared-donatable inputs must carry "
             "tf.aliasing_output/jax.buffer_donor in the lowered "
             "program",
    "GI002": "dtype policy: no f64; no f32 dot/conv under the bf16 "
             "policy unless allowlisted; quantized rungs must keep "
             "their i8 compute",
    "GI003": "host round-trips: no infeed/outfeed/send/recv or "
             "host-callback custom_call in hot-path programs",
    "GI004": "pad-waste: share of dot/conv flops spent on padding "
             "rows per bucket rung must stay under the budget",
    "GI005": "program-count budget: each subsystem's AOT program "
             "count must match its declared budget",
}
