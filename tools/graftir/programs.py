"""The representative AOT program set: every program family the
framework ships, lowered on tiny CPU avals and captured through the
SAME producer hooks ``MXNET_IR_AUDIT`` uses in production.

``python -m tools.graftir`` (and ``ci/graftir_smoke.py``) call
:func:`build_representative_set`; ``--check`` diffs the result
against the committed ``manifest.json``.  Everything here is
deterministic — fixed seeds, fixed shapes, lower-only for the serving
programs (the audited programs are never executed; the fused-step
capture drives one tiny CPU train step because the production hook
fires on first dispatch) — so the canonical-sha entries in the
manifest reproduce bit-for-bit.

Donation note: CPU jax reports ``supports_donation() == False``, so
the builders force the donation *declaration* (patch / ``donate=True``)
exactly like the existing CPU CI donation checks — GI001 audits the
declared aliasing in the lowered text, which is backend-independent.
"""

from __future__ import annotations

import contextlib
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# the shared rung geometry of the representative serve ladder: two
# rungs is the smallest set that exercises bucket routing + GI004
SERVE_RUNGS = (2, 8)
DECODE_SESSIONS = 2
QUANT_RUNG = 4


def _ensure_import_path():
    if _ROOT not in sys.path:
        sys.path.insert(0, _ROOT)


@contextlib.contextmanager
def _declared_donation():
    """Force the donation declaration on CPU (the fused-step builder
    reads ``ops.registry.supports_donation`` at program-build time)."""
    from mxnet_tpu.ops import registry as _reg
    orig = _reg.supports_donation
    _reg.supports_donation = lambda: True
    try:
        yield
    finally:
        _reg.supports_donation = orig


def _build_fused_step():
    """One tiny full-fused train step, captured via the production
    first-dispatch hook in ``Module._run_fused_full``."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, sym
    from mxnet_tpu.io import DataBatch

    rng = np.random.RandomState(7)
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")

    os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
    try:
        with _declared_donation():
            mod = mx.Module(net, context=mx.cpu())
            mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
            mod.init_params(arg_params={
                "fc1_weight": nd.array(
                    rng.randn(8, 6).astype(np.float32) * 0.1),
                "fc1_bias": nd.array(np.zeros(8, np.float32)),
                "fc2_weight": nd.array(
                    rng.randn(4, 8).astype(np.float32) * 0.1),
                "fc2_bias": nd.array(np.zeros(4, np.float32)),
            })
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.1})
            batch = DataBatch(
                data=[nd.array(rng.randn(4, 6).astype(np.float32))],
                label=[nd.array(
                    rng.randint(0, 4, 4).astype(np.float32))])
            mod.forward_backward_update(batch)
    finally:
        os.environ.pop("MXNET_MODULE_FUSED_STEP", None)


def _serve_predictor():
    import numpy as np
    from mxnet_tpu import nd, sym
    from mxnet_tpu.serve.buckets import BucketLadder
    from mxnet_tpu.serve.predictor import CompiledPredictor

    rng = np.random.RandomState(11)
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="sf1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="sf2")
    params = {
        "sf1_weight": nd.array(rng.randn(8, 6).astype(np.float32) * 0.1),
        "sf1_bias": nd.array(np.zeros(8, np.float32)),
        "sf2_weight": nd.array(rng.randn(4, 8).astype(np.float32) * 0.1),
        "sf2_bias": nd.array(np.zeros(4, np.float32)),
    }
    return CompiledPredictor(
        net, params, data_shapes={"data": (max(SERVE_RUNGS), 6)},
        ladder=BucketLadder(batches=SERVE_RUNGS), name="rep-mlp")


def _build_serve_rungs():
    """Every serve bucket rung, lower-only, declared through the same
    ``_audit_rung`` helper ``ensure_program`` uses."""
    pred = _serve_predictor()
    for b in SERVE_RUNGS:
        shapes = pred.rung_shapes(b)
        pred._audit_rung(None, shapes, pred.lowered_text(shapes))


def _build_decode_rungs():
    """One paged-decode tick rung + one prefill rung, lower-only, with
    the pool donation declared (donate=True, the CPU CI convention)."""
    from mxnet_tpu.serve.decode import DecodeEngine
    from mxnet_tpu.test_utils import tiny_attention_lm

    params, step_fn, prefill_fn, token_spec, input_spec = \
        tiny_attention_lm(vocab=16, dim=8, seed=3)
    eng = DecodeEngine(
        step_fn, prefill_fn=prefill_fn, token_spec=token_spec,
        input_spec=input_spec, params=params, max_len=16,
        block_size=4, num_blocks=24,
        session_rungs=(DECODE_SESSIONS,), prefill_rungs=(4, 16),
        donate=True, warm=False, label="rep-decode")
    eng._audit("tick", "S%d" % DECODE_SESSIONS,
               eng.lower_tick_text(DECODE_SESSIONS))
    eng._audit("prefill", "L4", eng.lower_prefill_text(4))


def _build_quantized_rungs():
    """One int8-quantized serve rung (calibrate -> quantize_model ->
    lower), declared with the quantize gate's dtype policy."""
    import numpy as np
    from mxnet_tpu import iraudit, nd, sym
    from mxnet_tpu.quantize import calibrate, quantize_model
    from mxnet_tpu.serve.buckets import BucketLadder
    from mxnet_tpu.serve.predictor import CompiledPredictor

    rng = np.random.RandomState(4)
    data = sym.var("data")
    c1 = sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                         name="qc1")
    a1 = sym.Activation(data=c1, act_type="relu", name="qa1")
    f1 = sym.FullyConnected(data=a1, num_hidden=4, name="qf1")
    params = {
        "qc1_weight": nd.array(
            rng.randn(8, 3, 3, 3).astype(np.float32) * 0.2),
        "qc1_bias": nd.array(rng.randn(8).astype(np.float32) * 0.1),
        "qf1_weight": nd.array(
            rng.randn(4, 8 * 10 * 10).astype(np.float32) * 0.1),
        "qf1_bias": nd.array(rng.randn(4).astype(np.float32) * 0.1),
    }
    batches = [rng.randn(4, 3, 12, 12).astype(np.float32)
               for _ in range(3)]
    table = calibrate(f1, params, batches)
    qsym, qargs, qaux, _report = quantize_model(
        f1, params, calib=table, policy="int8", name="rep-quant")
    qpred = CompiledPredictor(
        qsym, qargs, aux_params=qaux,
        data_shapes={"data": (QUANT_RUNG, 3, 12, 12)},
        ladder=BucketLadder(batches=(QUANT_RUNG,)), name="rep-quant")
    for b in qpred.ladder.batches:
        iraudit.audit(
            "quantize", "quantized/b%d" % b,
            qpred.lowered_text(qpred.rung_shapes(b)),
            model="rep-quant", dtype_policy="int8",
            budget=len(qpred.ladder.batches))


def build_representative_set():
    """Lower the full representative program set (CPU avals) and
    return the captured ``Program`` list."""
    _ensure_import_path()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mxnet_tpu import iraudit

    with iraudit.collect() as programs:
        _build_fused_step()
        _build_serve_rungs()
        _build_decode_rungs()
        _build_quantized_rungs()
    return list(programs)
