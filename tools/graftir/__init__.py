"""graftir — static analyzer + committed cost manifest for the
framework's lowered StableHLO programs.

graftlint audits Python source and graftsan audits runtime behavior;
graftir audits the *programs themselves*: the AOT StableHLO that the
fused train step, every serve bucket rung, every decode tick and
every quantized rung actually execute.  Rules GI001-GI005 turn
whole-program conventions (donation coverage, dtype policy, no host
round-trips, pad-waste budgets, program-count budgets) into checkable
facts, and the committed ``manifest.json`` makes per-program
flops/bytes a reviewable CI diff.

Run ``python -m tools.graftir --check`` (see docs/ir_audit.md).
"""

from .engine import (AuditEngine, Baseline, Finding, audit_programs,
                     DEFAULT_BASELINE)
from .hlo import Program, canonical_sha, canonicalize, cost_summary
from .manifest import (DEFAULT_MANIFEST, GROWTH_TOLERANCE, build, diff,
                       format_diff_table, load, save)
from .rules import ALL_RULES, RULE_DOCS

__all__ = [
    "AuditEngine", "Baseline", "Finding", "Program", "ALL_RULES",
    "RULE_DOCS", "audit_programs", "canonical_sha", "canonicalize",
    "cost_summary", "build", "diff", "load", "save",
    "format_diff_table", "DEFAULT_BASELINE", "DEFAULT_MANIFEST",
    "GROWTH_TOLERANCE",
]
