#!/usr/bin/env python
"""Offline serving autotuner — search the knob space against a
recorded trace, persist the winner to a TuningStore.

Drives :func:`mxnet_tpu.autotune.search.tune`: successive-halving
over the serve (bucket ladder + batcher window + row cap) or decode
(KV block size + session rungs + tick window) config space, every
ranking decision a REAL replay of an arrival trace through the real
serving machinery, with the ``observability.costs`` analytic prior
pruning dominated candidates before they cost a measurement.

    # record a trace from live-shaped load, then tune against it
    python bench.py --serve --record-trace /tmp/peak.trace.json
    python tools/autotune.py --workload serve --model bench \\
        --trace /tmp/peak.trace.json --store /tmp/tuning.json

    # serving processes pick the winner up at load time
    MXNET_TUNING_STORE=/tmp/tuning.json python bench.py --serve

No trace file = a synthetic open-loop trace (--rate/--seconds), good
for smoke runs; real tuning should replay recorded load.  The winner
is guarded: the default config is always measured at full budget on
the same trace, and if nothing beats it the default wins with gain 0
— a tuning run can never ship a regression (docs/autotuning.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_parser():
    p = argparse.ArgumentParser(
        prog="autotune.py",
        description="search serving configs against a replayed trace")
    p.add_argument("--workload", choices=("serve", "decode"),
                   default="serve")
    p.add_argument("--model", default="autotune",
                   help="store key: the registry/engine name that "
                        "should pick the tuning up at load time")
    p.add_argument("--trace", default=None,
                   help="recorded trace JSON (bench.py "
                        "--record-trace); default: synthesize one")
    p.add_argument("--store", default=None,
                   help="TuningStore JSON to create/update with the "
                        "winning entry (default: print only)")
    p.add_argument("--trials", type=int, default=12,
                   help="random proposals incl. the default config")
    p.add_argument("--neighbor-trials", type=int, default=4,
                   help="local perturbations of the short-round "
                        "leader")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--short-frac", type=float, default=0.25,
                   help="trace fraction of the screening replays")
    # synthetic-trace shape (ignored with --trace)
    p.add_argument("--rate", type=float, default=None,
                   help="synthetic arrivals/sec (default 150 serve, "
                        "12 decode)")
    p.add_argument("--seconds", type=float, default=None,
                   help="synthetic trace length (default 2 serve, "
                        "3 decode)")
    p.add_argument("--dim", type=int, default=64,
                   help="serve payload width of the synthetic trace")
    p.add_argument("--json", action="store_true",
                   help="dump the full result dict as JSON")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-trial progress lines")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from mxnet_tpu.autotune import (Trace, TuningStore, decode_space,
                                    serve_space, synth_decode_trace,
                                    synth_serve_trace, tune)
    from mxnet_tpu.autotune.measure import DecodeMeasurer, ServeMeasurer
    from mxnet_tpu.autotune.search import (decode_objective,
                                           serve_objective)

    if args.trace:
        trace = Trace.load(args.trace)
        if trace.kind != args.workload:
            print("error: %s is a %r trace but --workload is %r"
                  % (args.trace, trace.kind, args.workload),
                  file=sys.stderr)
            return 2
    elif args.workload == "serve":
        trace = synth_serve_trace(rate=args.rate or 150.0,
                                  seconds=args.seconds or 2.0,
                                  dim=args.dim)
    else:
        trace = synth_decode_trace(rate=args.rate or 12.0,
                                   seconds=args.seconds or 3.0)
    s = trace.summary()
    print("trace: kind=%(kind)s events=%(events)d "
          "duration=%(duration_s).2fs sha256=%(sha256).12s" % s)

    if args.workload == "serve":
        space = serve_space()
        measurer = ServeMeasurer(trace, name=args.model)
        objective = serve_objective()
    else:
        space = decode_space()
        measurer = DecodeMeasurer(trace, name=args.model)
        objective = decode_objective()

    store = TuningStore.load(args.store, missing_ok=True) \
        if args.store else None
    log = (lambda *_a: None) if args.quiet else \
        (lambda msg: print("  " + msg))
    try:
        result = tune(space, measurer, objective,
                      model=args.model, workload=args.workload,
                      trials=args.trials,
                      neighbor_trials=args.neighbor_trials,
                      seed=args.seed, short_frac=args.short_frac,
                      store=store, log=log)
    finally:
        measurer.close()

    if args.json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        print("winner: %s" % json.dumps(result["config"],
                                        sort_keys=True, default=list))
        print("score: %s (baseline %s, objective %s)"
              % (result["score"], result["baseline_score"],
                 result["objective"]["name"]))
        if args.store:
            print("stored: %s -> %s|%s|%s"
                  % (args.store, result["model"],
                     result["device_kind"], result["workload"]))
    # scrapeable summary — keep in sync with ci/autotune_smoke.py
    print("autotune: trials=%d pruned=%d winner_gain=%s%% ok"
          % (result["trials"], result["pruned"], result["gain_pct"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
