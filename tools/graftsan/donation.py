"""graftsan donation sanitizer.

The fused train step donates the parameter and optimizer-state buffers
(``donate_argnums``) — after dispatch, every *other* reference to those
buffers points at memory XLA has already reused.  jax does raise on a
deleted buffer eventually, but deep inside XLA with a message that
names no one.  This component walks the live NDArray wrappers after a
donating dispatch and **poisons** every stale alias: its ``_data`` is
replaced with a proxy that raises :class:`UseAfterDonateError` at the
touch site, naming the donation site and step.

Poisoning keys on the *declared* donation (what was passed at donated
argnum positions), not on whether the backend honored it — the CPU
backend ignores donation, but code that aliases a donated buffer is
already wrong on TPU, and the sanitizer's job is to catch that in CPU
CI before it ships.
"""

from __future__ import annotations

import gc

from .report import capture_stack, report

__all__ = ["UseAfterDonateError", "PoisonedBuffer", "poison_stale_aliases",
           "poison_ndarray"]


class UseAfterDonateError(RuntimeError):
    """A buffer donated to an XLA program was touched afterwards."""


class PoisonedBuffer:
    """Stands in for a donated jax array; any use raises with the
    donation site."""

    __slots__ = ("_san_msg",)

    def __init__(self, msg):
        object.__setattr__(self, "_san_msg", msg)

    def _raise(self):
        msg = object.__getattribute__(self, "_san_msg")
        report("donation", "use-after-donate", msg,
               [("touch site", capture_stack())])
        raise UseAfterDonateError(msg)

    def __getattr__(self, name):
        self._raise()

    def __repr__(self):
        return "<graftsan poisoned buffer: %s>" % \
            object.__getattribute__(self, "_san_msg")

    def __array__(self, *a, **kw):
        self._raise()

    def __bool__(self):
        self._raise()

    def __len__(self):
        self._raise()

    def __getitem__(self, key):
        self._raise()

    def __iter__(self):
        self._raise()

    def __float__(self):
        self._raise()

    def __int__(self):
        self._raise()


def poison_ndarray(arr, site):
    """Poison one NDArray wrapper in place."""
    msg = ("buffer of %s NDArray was donated to %s and must not be "
           "touched afterwards — XLA reuses donated buffers for the "
           "program's outputs; read the step's RESULT arrays instead, "
           "or copy before the step" % (
               getattr(arr, "shape", "?"), site))
    arr._data = PoisonedBuffer(msg)
    return arr


def poison_stale_aliases(donated_leaves, site, ndarray_cls=None):
    """Find every live NDArray whose ``_data`` is one of
    *donated_leaves* (identity match) and poison it.

    Runs only under ``MXNET_SAN=donation``, so the gc sweep's cost is
    acceptable; the rebinding the framework does for its own containers
    (arg_dict/aux_dict/updater states) happens BEFORE this call, so
    anything still holding a donated leaf is a stale alias by
    construction.  Returns the number of aliases poisoned."""
    if ndarray_cls is None:
        from mxnet_tpu.ndarray import NDArray as ndarray_cls
    ids = {id(l) for l in donated_leaves if l is not None}
    if not ids:
        return 0
    n = 0
    for obj in gc.get_objects():
        if isinstance(obj, ndarray_cls):
            data = getattr(obj, "_data", None)
            if data is not None and id(data) in ids:
                poison_ndarray(obj, site)
                n += 1
    return n
