"""graftsan — opt-in runtime sanitizer suite for the mxnet_tpu tree.

graftlint (tools/graftlint) catches JAX hazards visible in the AST;
graftsan catches the dynamic ones: unsynchronized shared state in the
threaded subsystems, unexpected jit-cache churn, use of donated
buffers, and silent device→host syncs in the training hot path.  The
pairing mirrors how TVM and Glow back their compilers with
verification tooling — statically where possible, dynamically where
the AST can't see.

Activation — zero overhead when off::

    MXNET_SAN=race,recompile,donation,transfer   # or 'all' / 'on'
    pytest --graftsan                            # tests/conftest.py flag

Components
----------
race       instrumented Lock/RLock/Condition wrappers + an
           Eraser-style per-object per-attribute lockset tracker
           (empty lockset intersection across ≥2 threads with a write
           ⇒ report with both stacks) + a lock-order cycle checker
race.py, recompile.py, donation.py, transfer.py hold the components;
report.py collects findings.  Production code reaches them only
through the ``mxnet_tpu.sanitizer`` bridge, which no-ops (and never
imports this package) unless ``MXNET_SAN`` enables a component.

The static companions are graftlint's JG010 (attribute written both
with and without the lock that guards it elsewhere) and JG011 (thread
started without join/daemon ownership) — seeded from the patterns the
runtime wrappers surfaced.  See docs/sanitizers.md.
"""

from __future__ import annotations

import os

from . import donation, race, recompile, report, transfer  # noqa: F401
from .report import clear, format_report, reports  # noqa: F401

__version__ = "1.0"

COMPONENTS = ("race", "recompile", "donation", "transfer")


def parse_spec(raw=None):
    """``MXNET_SAN`` value -> frozenset of enabled components."""
    if raw is None:
        raw = os.environ.get("MXNET_SAN", "")
    raw = (raw or "").strip().lower()
    if not raw or raw in ("0", "off", "none", "false"):
        return frozenset()
    if raw in ("1", "on", "all", "true"):
        return frozenset(COMPONENTS)
    comps = frozenset(p.strip() for p in raw.split(",") if p.strip())
    unknown = comps - frozenset(COMPONENTS)
    if unknown:
        raise ValueError(
            "MXNET_SAN names unknown sanitizer component(s) %s "
            "(known: %s)" % (sorted(unknown), ", ".join(COMPONENTS)))
    return comps
