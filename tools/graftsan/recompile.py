"""graftsan recompile sanitizer.

The fused-train-step contract (docs/perf_fused_step.md) is *one jitted
dispatch and zero compiles per step after warmup*.  The profiler's
always-on ``fused_step_compiles``/``*_dispatches`` counters observe
violations, but they can't say WHY a step recompiled.  This component
wraps a jitted callable, watches its jit cache, and on any cache miss
after warmup diffs the call signature against the previous call's to
blame the exact leaf (arg path, shape, dtype, weak-type, or static
value) that churned.
"""

from __future__ import annotations

import threading

from .report import capture_stack, report

__all__ = ["JitWatch", "wrap_jit", "signature", "diff_signatures"]


def _leaf_sig(x):
    """Hashable description of one argument leaf.  Includes
    committedness and device placement: jax keys its jit cache on them,
    and an uncommitted-at-warmup array silently doubles compilation
    (the exact bug this sanitizer caught in the fused step)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        weak = getattr(x, "weak_type", False)
        committed = getattr(x, "_committed", None)
        sharding = getattr(x, "sharding", None)
        devs = None
        if sharding is not None:
            try:
                devs = tuple(sorted(d.id for d in sharding.device_set))
            except Exception:
                devs = str(sharding)
        return ("array", tuple(shape), str(dtype), bool(weak),
                committed, devs)
    return ("static", type(x).__name__, repr(x)[:80])


def signature(args, kwargs=None):
    """{path: leaf signature} over the flattened call arguments."""
    from jax.tree_util import tree_flatten_with_path, keystr
    leaves, _ = tree_flatten_with_path((args, dict(kwargs or {})))
    return {keystr(path): _leaf_sig(leaf) for path, leaf in leaves}


def diff_signatures(prev, cur):
    """Human-readable lines describing what changed between two call
    signatures — array-metadata and pytree-structure changes first
    (those retrace), plain scalar value changes last (those usually
    don't; they matter only at static_argnums positions)."""
    likely, unlikely = [], []
    for path in sorted(set(prev) | set(cur)):
        a, b = prev.get(path), cur.get(path)
        if a == b:
            continue
        if a is None:
            likely.append("  + %s: %r (new leaf — pytree structure "
                          "changed)" % (path, b))
        elif b is None:
            likely.append("  - %s: %r (leaf gone — pytree structure "
                          "changed)" % (path, a))
        elif a[0] == "static" and b[0] == "static":
            unlikely.append("  ? %s: %r -> %r (python scalar value — "
                            "retraces only at a static_argnums "
                            "position)" % (path, a, b))
        else:
            likely.append("  ~ %s: %r -> %r" % (path, a, b))
    return likely + unlikely


class JitWatch:
    """Callable proxy over a jitted function that reports blamed cache
    misses.  Transparent otherwise (``__getattr__`` delegates, so
    ``_cache_size``/``lower``/... remain reachable)."""

    def __init__(self, fn, name):
        self._fn = fn
        self._name = name
        self._lock = threading.Lock()
        self._last_sig = None
        self._calls = 0

    def __call__(self, *args, **kwargs):
        size_of = getattr(self._fn, "_cache_size", None)
        before = size_of() if size_of else None
        out = self._fn(*args, **kwargs)
        after = size_of() if size_of else None
        sig = signature(args, kwargs)
        with self._lock:
            missed = (after is not None and before is not None
                      and after > before)
            if missed and self._calls >= 1 and self._last_sig is not None:
                lines = diff_signatures(self._last_sig, sig)
                why = "\n".join(lines) if lines else \
                    "  (signature identical — miss caused by a new " \
                    "callable identity or a cleared cache)"
                report(
                    "recompile", "cache-miss",
                    "'%s' recompiled at call %d (jit cache %d -> %d). "
                    "Churned leaves:\n%s"
                    % (self._name, self._calls + 1, before, after, why),
                    [("recompiling call", capture_stack())])
            self._last_sig = sig
            self._calls += 1
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def wrap_jit(fn, name):
    """Wrap *fn* (a jitted callable) in a :class:`JitWatch`."""
    if isinstance(fn, JitWatch):
        return fn
    return JitWatch(fn, name)
