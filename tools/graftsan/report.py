"""graftsan report collector.

Every sanitizer component funnels its findings through :func:`report`.
Reports are collected (thread-safely) rather than raised: a sanitizer
must observe the program, not alter its control flow — the exceptions
are the donation poison and the transfer guard, which raise *at the
touch site* by design (the whole point is a loud error where the bug
is).  The pytest plugin and the CI smoke stage fail the run when
:func:`reports` is non-empty at the end.
"""

from __future__ import annotations

import logging
import os
import threading
import traceback

log = logging.getLogger("graftsan")

#: frames to drop from report stacks: this package's own files and the
#: mxnet_tpu.sanitizer bridge — matched by PATH, not substring, so
#: user code that merely mentions graftsan (tests, the CI smoke
#: script) keeps its frames
_OWN_DIR = os.path.dirname(os.path.abspath(__file__)) + os.sep
_BRIDGE_SUFFIX = os.path.join("mxnet_tpu", "sanitizer.py")

__all__ = ["Report", "report", "reports", "clear", "format_report",
           "capture_stack"]


class Report:
    """One sanitizer finding."""

    __slots__ = ("component", "kind", "message", "stacks")

    def __init__(self, component, kind, message, stacks=()):
        self.component = component      # race | recompile | donation | ...
        self.kind = kind                # e.g. 'lockset', 'lock-order'
        self.message = message
        #: list of (label, formatted stack string)
        self.stacks = list(stacks)

    def __repr__(self):
        return "graftsan[%s/%s]: %s" % (self.component, self.kind,
                                        self.message)


_reports = []
_lock = threading.Lock()


def capture_stack(limit=14):
    """A trimmed formatted stack of the calling thread, with graftsan's
    own frames (and the bridge's) dropped — the report should point at
    user code."""
    frames = traceback.extract_stack()
    frames = [f for f in frames
              if not f.filename.startswith(_OWN_DIR)
              and not f.filename.endswith(_BRIDGE_SUFFIX)]
    return "".join(traceback.format_list(frames[-limit:]))


def report(component, kind, message, stacks=()):
    r = Report(component, kind, message, stacks)
    with _lock:
        _reports.append(r)
    log.warning("%s", format_report(r))
    return r


def reports(component=None):
    with _lock:
        if component is None:
            return list(_reports)
        return [r for r in _reports if r.component == component]


def clear():
    with _lock:
        _reports.clear()


def format_report(r):
    out = ["graftsan [%s/%s] %s" % (r.component, r.kind, r.message)]
    for label, stack in r.stacks:
        out.append("  -- %s:" % label)
        out.extend("  | " + ln for ln in stack.rstrip().splitlines())
    return "\n".join(out)
