"""graftsan race detector.

Three cooperating pieces:

1. **Instrumented lock primitives** (:func:`lock`, :func:`rlock`,
   :func:`condition`, :func:`event`, :func:`queue_`, :func:`thread`)
   that production code creates through the ``mxnet_tpu.sanitizer``
   bridge.  Each wrapper maintains the calling thread's *held-lock
   set* and feeds the lock-order graph.

2. **A lockset (Eraser-style) shared-attribute tracker**
   (:func:`track_object`): production classes whose attributes are
   touched from several threads register the attribute names; every
   read/write records ``(thread, currently-held locks)``.  The
   per-(object, attr) candidate lockset is the intersection of the
   locksets of all accesses after the attribute became shared; an
   empty candidate set once a second thread has *written* means no
   single lock consistently guards the attribute — reported once,
   with the stacks of both conflicting threads.  The state machine
   (virgin → exclusive → shared → shared-modified) keeps
   single-threaded construction and thread handoff quiet.

3. **A lock-order (deadlock-cycle) checker**: acquiring B while
   holding A records the edge A→B; an acquisition that closes a cycle
   in the global edge graph is reported with both acquisition stacks,
   whether or not the schedule actually deadlocked this run.

Everything here is only imported when ``MXNET_SAN`` enables the race
component — the production bridge falls back to the plain ``threading``
primitives otherwise, so the off cost is one env check at *creation*
time and zero per access.
"""

from __future__ import annotations

import itertools
import queue as _queue_mod
import threading

from .report import capture_stack, report

__all__ = ["lock", "rlock", "condition", "event", "queue_", "thread",
           "track_object", "held_locks", "reset"]

_tls = threading.local()


def _held():
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def held_locks():
    """Ids of instrumented locks the calling thread currently holds."""
    return frozenset(l._san_id for l in _held())


# ---------------------------------------------------------------------------
# lock-order graph
# ---------------------------------------------------------------------------

_graph_lock = threading.Lock()      # deliberately raw: guards the detector
_edges = {}          # lock id -> {successor lock id}
_edge_sites = {}     # (a, b) -> (a label, b label, stack at first obs)
_reported_cycles = set()
_ids = itertools.count(1)


def _note_acquire_order(lk):
    held = _held()
    if not held:
        return
    bid = lk._san_id
    with _graph_lock:
        for h in held:
            aid = h._san_id
            if aid == bid:
                continue
            succ = _edges.setdefault(aid, set())
            if bid not in succ:
                succ.add(bid)
                _edge_sites[(aid, bid)] = (h._san_label, lk._san_label,
                                           capture_stack())
            # does bid already reach aid?  then aid->bid closes a cycle
            if _reaches(bid, aid):
                key = frozenset((aid, bid))
                if key not in _reported_cycles:
                    _reported_cycles.add(key)
                    fwd = _edge_sites.get((aid, bid))
                    rev = _edge_sites.get((bid, aid))
                    stacks = []
                    if fwd:
                        stacks.append(("%s -> %s acquired here"
                                       % (fwd[0], fwd[1]), fwd[2]))
                    if rev:
                        stacks.append(("%s -> %s acquired here"
                                       % (rev[0], rev[1]), rev[2]))
                    report(
                        "race", "lock-order",
                        "lock-order cycle: '%s' and '%s' are acquired "
                        "in both orders — two threads interleaving "
                        "these paths deadlock"
                        % (h._san_label, lk._san_label), stacks)


def _reaches(src, dst, _seen=None):
    if src == dst:
        return True
    seen = _seen if _seen is not None else set()
    seen.add(src)
    for nxt in _edges.get(src, ()):
        if nxt not in seen and _reaches(nxt, dst, seen):
            return True
    return False


# ---------------------------------------------------------------------------
# instrumented primitives
# ---------------------------------------------------------------------------

class _InstrumentedLock:
    """Wraps a real Lock/RLock; context-manager compatible."""

    _reentrant = False

    def __init__(self, label=None):
        self._real = (threading.RLock() if self._reentrant
                      else threading.Lock())
        self._san_id = next(_ids)
        self._san_label = label or ("%s#%d" % (
            "RLock" if self._reentrant else "Lock", self._san_id))
        self._depth = {}        # thread ident -> reentrant depth

    def acquire(self, blocking=True, timeout=-1):
        tid = threading.get_ident()
        first = self._depth.get(tid, 0) == 0
        if first:
            _note_acquire_order(self)
        got = self._real.acquire(blocking, timeout)
        if got:
            self._depth[tid] = self._depth.get(tid, 0) + 1
            if first:
                _held().append(self)
        return got

    def release(self):
        tid = threading.get_ident()
        self._real.release()
        d = self._depth.get(tid, 1) - 1
        if d:
            self._depth[tid] = d
        else:
            self._depth.pop(tid, None)
            held = _held()
            if self in held:
                held.remove(self)

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") \
            else bool(self._depth)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return "<graftsan %s>" % self._san_label


class _InstrumentedRLock(_InstrumentedLock):
    _reentrant = True


class _InstrumentedCondition:
    """threading.Condition over an instrumented lock; ``wait`` hands
    the lock back to the scheduler, so held-tracking pops/pushes
    around it."""

    def __init__(self, lock=None, label=None):
        self._lk = lock if lock is not None else _InstrumentedRLock(
            label=(label or "Condition") + ".lock")
        self._real = threading.Condition(self._lk._real)
        self._san_label = label or "Condition#%d" % self._lk._san_id

    def acquire(self, *a, **kw):
        return self._lk.acquire(*a, **kw)

    def release(self):
        self._lk.release()

    def __enter__(self):
        self._lk.acquire()
        return self

    def __exit__(self, *exc):
        self._lk.release()

    def _unheld(self):
        held = _held()
        if self._lk in held:
            held.remove(self._lk)

    def _reheld(self):
        _held().append(self._lk)

    def wait(self, timeout=None):
        self._unheld()
        try:
            return self._real.wait(timeout)
        finally:
            self._reheld()

    def wait_for(self, predicate, timeout=None):
        self._unheld()
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            self._reheld()

    def notify(self, n=1):
        self._real.notify(n)

    def notify_all(self):
        self._real.notify_all()


def lock(label=None):
    return _InstrumentedLock(label)


def rlock(label=None):
    return _InstrumentedRLock(label)


def condition(lock=None, label=None):
    return _InstrumentedCondition(lock, label)


def event():
    # Event is already race-free by contract; returned raw so waiters
    # are unaffected, kept in the API so the bridge covers the full set
    return threading.Event()


def queue_(maxsize=0):
    # queue.Queue's internal mutex is a raw allocation (not routed
    # through the bridge), so its hand-offs never pollute locksets;
    # the queue itself is the synchronization, nothing to instrument
    return _queue_mod.Queue(maxsize)


_thread_sites = {}   # thread ident -> (name, creation stack)
_thread_lock = threading.Lock()


def thread(group=None, target=None, name=None, args=(), kwargs=None,
           daemon=None):
    """threading.Thread that registers its creation stack, so race
    reports can say where a conflicting thread was started."""
    site = capture_stack()
    kwargs = kwargs or {}

    def run(*a, **kw):
        with _thread_lock:
            _thread_sites[threading.get_ident()] = (
                threading.current_thread().name, site)
        return target(*a, **kw) if target is not None else None

    return threading.Thread(group=group, target=run, name=name,
                            args=args, kwargs=kwargs, daemon=daemon)


def thread_site(ident):
    with _thread_lock:
        return _thread_sites.get(ident)


# ---------------------------------------------------------------------------
# lockset shared-attribute tracker (Eraser state machine)
# ---------------------------------------------------------------------------

VIRGIN, EXCLUSIVE, SHARED, SHARED_MOD = range(4)

_state_lock = threading.Lock()   # raw: guards detector bookkeeping
_tracked_classes = {}


class _AttrState:
    __slots__ = ("state", "owner", "lockset", "last", "reported")

    def __init__(self):
        self.state = VIRGIN
        self.owner = None       # first-owner thread ident
        self.lockset = None     # frozenset of lock ids, None until shared
        self.last = {}          # ident -> (op, stack)
        self.reported = False


def _record_access(obj, attr, op):
    d = object.__getattribute__(obj, "__dict__")
    label = d.get("_graftsan_label", type(obj).__name__)
    tid = threading.get_ident()
    cur = held_locks()
    stack = capture_stack()
    with _state_lock:
        states = d.setdefault("_graftsan_attr_state", {})
        st = states.get(attr)
        if st is None:
            st = states[attr] = _AttrState()
        st.last[tid] = (op, stack)
        if st.state == VIRGIN:
            st.state = EXCLUSIVE
            st.owner = tid
            return
        if st.state == EXCLUSIVE:
            if tid == st.owner:
                return
            # second thread: attribute became shared; candidate lockset
            # starts from THIS access (the exclusive phase is exempt —
            # single-threaded construction / clean handoff)
            st.state = SHARED_MOD if op == "write" else SHARED
            st.lockset = cur
        else:
            st.lockset = st.lockset & cur
            if op == "write":
                st.state = SHARED_MOD
        if (st.state == SHARED_MOD and not st.lockset
                and not st.reported and len(st.last) >= 2):
            st.reported = True
            # the CURRENT access is the one that drained the candidate
            # lockset — it must be in the report (dict insertion order
            # would keep an old slot for a re-accessing thread and
            # could print two innocent threads instead)
            others = [t for t in reversed(list(st.last)) if t != tid]
            stacks = []
            for t in (tid, others[0]):
                o, s = st.last[t]
                who = "thread %d (%s)" % (t, o)
                site = thread_site(t)
                if site:
                    who += " started as %r" % site[0]
                stacks.append((who, s))
            report(
                "race", "lockset",
                "%s.%s is accessed from %d threads with no common "
                "lock (at least one access is a write) — "
                "unsynchronized shared state"
                % (label, attr, len(st.last)), stacks)


def _make_tracked_class(cls):
    tracked = _tracked_classes.get(cls)
    if tracked is not None:
        return tracked

    class Tracked(cls):
        __graftsan_tracked__ = True

        def __getattribute__(self, name):
            value = super().__getattribute__(name)
            if name.startswith("_graftsan"):
                return value
            attrs = object.__getattribute__(self, "__dict__").get(
                "_graftsan_attrs")
            if attrs is not None and name in attrs:
                _record_access(self, name, "read")
            return value

        def __setattr__(self, name, value):
            attrs = object.__getattribute__(self, "__dict__").get(
                "_graftsan_attrs")
            if attrs is not None and name in attrs:
                _record_access(self, name, "write")
            super().__setattr__(name, value)

    Tracked.__name__ = cls.__name__
    Tracked.__qualname__ = cls.__qualname__
    _tracked_classes[cls] = Tracked
    return Tracked


def track_object(obj, attrs, label=None):
    """Enable lockset tracking of *attrs* on *obj* (its class is
    swapped for a cached tracked subclass).  Call at the END of
    ``__init__`` — construction writes stay out of the analysis."""
    cls = type(obj)
    if getattr(cls, "__graftsan_tracked__", False):
        cls = cls.__mro__[1]
    d = object.__getattribute__(obj, "__dict__")
    d["_graftsan_attrs"] = frozenset(attrs)
    d["_graftsan_label"] = label or cls.__name__
    obj.__class__ = _make_tracked_class(cls)
    return obj


def reset():
    """Clear detector state (tests)."""
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _reported_cycles.clear()
    with _thread_lock:
        _thread_sites.clear()
