"""graftsan host-transfer guard.

Marks a region of the training hot path (the fused/partial-fused step
dispatch, the tree_opt sweep) as *transfer-free*: any device→host sync
inside it raises :class:`HostTransferError` at the touch site instead
of silently serializing the pipeline.

Two layers, because the backends differ:

* ``jax.transfer_guard_device_to_host('disallow')`` — catches raw
  d2h copies on real device backends (TPU).  On the CPU backend a
  "transfer" is zero-copy and never engages jax's guard, so this
  layer alone is untestable in CPU CI.
* an NDArray-level choke point — ``NDArray.asnumpy`` (which
  ``asscalar``/``item``/``__float__``/``tolist`` all route through)
  checks a thread-local depth and raises inside a guarded region.
  This works on every backend and catches the framework-level sync
  even when the buffer happens to live on host.

Only the d2h direction is guarded: the fused step legitimately passes
host scalars (lrs/wds/ts/step) as jit arguments, and a full
``jax.transfer_guard('disallow')`` would reject those h2d constant
uploads.
"""

from __future__ import annotations

import contextlib
import threading

from .report import capture_stack, report

__all__ = ["HostTransferError", "guard", "check", "active"]


class HostTransferError(RuntimeError):
    """A device→host sync happened inside a transfer-guarded region."""


_tls = threading.local()


def active():
    return getattr(_tls, "depth", 0) > 0


@contextlib.contextmanager
def guard(label="hot path"):
    """Disallow device→host syncs in the dynamic extent."""
    import jax
    _tls.depth = getattr(_tls, "depth", 0) + 1
    prev_label = getattr(_tls, "label", None)
    _tls.label = label
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        _tls.depth -= 1
        # restore: a report raised later in a still-active OUTER region
        # must name the outer label, not this exited one
        _tls.label = prev_label


def check(what, shape=None):
    """Called from the NDArray d2h choke point; raises when guarded."""
    if not active():
        return
    label = getattr(_tls, "label", "hot path")
    msg = ("%s inside transfer-guarded region '%s' forces a device->host "
           "sync%s — hot-path host reads serialize the device pipeline; "
           "move the read outside the step or keep it device-side"
           % (what, label,
              " (shape %s)" % (shape,) if shape is not None else ""))
    report("transfer", "d2h", msg, [("touch site", capture_stack())])
    raise HostTransferError(msg)
