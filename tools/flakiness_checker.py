#!/usr/bin/env python
"""Flakiness checker: run one test many times with varied seeds
(reference: tools/flakiness_checker.py — the triage tool for
intermittently failing tests).

    python tools/flakiness_checker.py tests/test_operator.py::test_foo
    python tools/flakiness_checker.py test_operator.test_foo -n 100

Accepts either pytest node-id syntax (path::name) or the reference's
module.test syntax, runs the test N times with MXNET_TEST_SEED varied
per trial, and reports the failure count (exit 1 if any trial failed).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def to_nodeid(spec):
    if "::" in spec or os.path.exists(spec.split("::")[0]):
        return spec
    # reference syntax: test_module.test_name
    mod, _, name = spec.rpartition(".")
    path = os.path.join("tests", mod + ".py")
    return "%s::%s" % (path, name) if name else path


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("test", help="pytest node id or module.test_name")
    ap.add_argument("-n", "--num-trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=None,
                    help="fixed seed for every trial (default: trial #)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    nodeid = to_nodeid(args.test)
    failures = 0
    for trial in range(args.num_trials):
        env = dict(os.environ)
        env["MXNET_TEST_SEED"] = str(args.seed if args.seed is not None
                                     else trial)
        res = subprocess.run(
            [sys.executable, "-m", "pytest", nodeid, "-q", "-x"],
            capture_output=True, text=True, env=env)
        ok = res.returncode == 0
        failures += 0 if ok else 1
        if not args.quiet or not ok:
            print("trial %3d seed=%s : %s"
                  % (trial, env["MXNET_TEST_SEED"],
                     "ok" if ok else "FAILED"), flush=True)
        if not ok and not args.quiet:
            print(res.stdout[-1500:])
    print("%d/%d trials failed" % (failures, args.num_trials))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
