"""Flash-attention kernel sweep: numerics + TF/s, fwd AND bwd, on the
real chip.

One command produces everything VERDICT round 3 asked for: per-config
numeric checks of the Pallas kernels against the einsum oracle
(forward and all three gradients), then a block-size timing sweep with
useful-FLOP throughput for forward, backward, and the chunked-XLA
baseline.

    PYTHONPATH=/root/repo:/root/.axon_site python tools/flash_sweep.py

Timing discipline per docs/PERF_NOTES.md: iterations are chained
through a data dependency inside one jit (scan), timed to a host
readback.  Safe on a healthy tunnel only — run bench.py's probe first
(tools/tpu_round4.sh sequences this).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def numeric_check(shapes=(1, 2, 256, 64)):
    """Flash (compiled, on-device) vs oracle: fwd + dq/dk/dv."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import (attention_reference,
                                         flash_attention)
    b, h, s, d = shapes
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)

    for causal in (False, True):
        def loss_f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal)
                           .astype(jnp.float32) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(attention_reference(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=causal) ** 2)

        out_f = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=causal))(q, k, v)
        out_r = attention_reference(q.astype(jnp.float32),
                                    k.astype(jnp.float32),
                                    v.astype(jnp.float32), causal=causal)
        fwd_err = float(jnp.max(jnp.abs(out_f.astype(jnp.float32) -
                                        out_r)))
        gf = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32) - b)))
                for a, b in zip(gf, gr)]
        scale = float(jnp.max(jnp.abs(out_r))) + 1e-6
        gscales = [float(jnp.max(jnp.abs(g))) + 1e-6 for g in gr]
        print(json.dumps({"check": "numerics", "causal": causal,
                          "fwd_maxerr": fwd_err,
                          "grad_maxerr": errs,
                          "out_scale": scale,
                          "grad_scales": gscales}), flush=True)
        assert fwd_err < 0.12 * scale, "forward mismatch"
        for which, e, gs in zip("dq dk dv".split(), errs, gscales):
            assert e < 0.15 * gs, "%s mismatch (%g vs scale %g)" \
                % (which, e, gs)


def _time_scan(fn, args, iters):
    """Chained timing: scan fn iters times inside ONE dispatch."""
    import jax
    import jax.numpy as jnp

    def chained(*args):
        def body(c, _):
            out = fn(*((c,) + args[1:]))
            # feed a scaled output back as q to chain the iterations
            return (c * 0 + out).astype(args[0].dtype), None
        c, _ = jax.lax.scan(body, args[0], None, length=iters)
        return jnp.sum(c.astype(jnp.float32))

    j = jax.jit(chained)
    float(j(*args))  # compile + warm
    t0 = time.perf_counter()
    float(j(*args))
    return (time.perf_counter() - t0) / iters


def sweep(b=4, h=16, s=4096, d=128, causal=True, iters=8):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import attention as A

    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, h, s, d), jnp.bfloat16)
    # useful flops: 2 dots of 2*s*s*d per head, halved by causal masking
    flops = 4.0 * b * h * s * s * d * (0.5 if causal else 1.0)
    results = []
    for blk in (256, 512, 1024, 2048):
        def fwd(q, k, v):
            return A._flash_fwd_pallas(q, k, v, causal,
                                       1.0 / (d ** 0.5),
                                       blk_q=blk, blk_k=blk)

        dt = _time_scan(fwd, (q, k, v), iters)
        row = {"metric": "flash_fwd", "blk": blk, "ms": dt * 1e3,
               "tflops": flops / dt / 1e12}
        results.append(row)
        print(json.dumps(row), flush=True)

        def bwd(q, k, v):
            out, lse = A._flash_fwd_pallas(
                q, k, v, causal, 1.0 / (d ** 0.5), blk_q=blk,
                blk_k=blk, with_lse=True)
            dout = jnp.ones_like(out)
            dq, dk, dv = A._flash_bwd_pallas(
                q, k, v, out, lse, dout, causal, 1.0 / (d ** 0.5),
                blk_q=blk, blk_k=blk)
            # consume dk/dv too: returning dq alone would let XLA
            # dead-code-eliminate the whole dkdv kernel and inflate
            # the reported throughput
            return dq + (jnp.sum(dk.astype(jnp.float32)) +
                         jnp.sum(dv.astype(jnp.float32))
                         ).astype(dq.dtype)

        dt = _time_scan(bwd, (q, k, v), iters)
        # bwd ~ 2.5x fwd flops (recompute + 4 grad dots over 2 fwd dots)
        row = {"metric": "flash_fwd_plus_bwd", "blk": blk,
               "ms": dt * 1e3, "tflops": 3.5 * flops / dt / 1e12}
        results.append(row)
        print(json.dumps(row), flush=True)

    def chunked(q, k, v):
        return A._chunked_attention(q, k, v, causal=causal)

    dt = _time_scan(chunked, (q, k, v), iters)
    row = {"metric": "chunked_xla_fwd", "ms": dt * 1e3,
           "tflops": flops / dt / 1e12}
    results.append(row)
    print(json.dumps(row), flush=True)
    best = max(r["tflops"] for r in results if r["metric"] == "flash_fwd")
    print(json.dumps({"metric": "flash_fwd_best_tflops", "value": best}))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    args = ap.parse_args()
    numeric_check()
    if not args.skip_sweep:
        sweep(s=args.seq, iters=args.iters)
    return 0


if __name__ == "__main__":
    sys.exit(main())
