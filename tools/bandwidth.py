#!/usr/bin/env python
"""KVStore bandwidth harness (reference: tools/bandwidth/measure.py —
push/pull throughput over the comm backend).

Measures aggregate push+pull bandwidth for a list of tensor sizes over
any kvstore type: `local`, `tpu` (in-graph ICI collectives), or
`dist_sync` (TCP parameter server; run under tools/launch.py).

Usage:
    python tools/bandwidth.py --kv-store local --sizes 1e5,1e6,1e7
    python tools/launch.py -n 2 -- python tools/bandwidth.py \
        --kv-store dist_sync
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))


def measure(kv, size, repeat, n_parts):
    """Aggregate push+pull GB/s for one tensor size (float32)."""
    import mxnet_tpu as mx
    shape = (int(size),)
    key = "bw_%d_%d" % (size, measure._seq)
    measure._seq += 1  # unique key even for duplicate --sizes entries
    kv.init(key, mx.nd.zeros(shape))
    vals = [mx.nd.ones(shape) for _ in range(n_parts)]
    out = mx.nd.zeros(shape)
    # warm (a 1-element list and a scalar push are equivalent)
    kv.push(key, vals)
    kv.pull(key, out=out)
    float(np.asarray(out.asnumpy()[0]))
    t0 = time.perf_counter()
    for _ in range(repeat):
        kv.push(key, vals)
        kv.pull(key, out=out)
    float(np.asarray(out.asnumpy()[0]))  # sync
    dt = time.perf_counter() - t0
    nbytes = 4 * size * repeat * (n_parts + 1)  # pushes + one pull
    return nbytes / dt / 1e9


measure._seq = 0


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--sizes", default="1e5,1e6,1e7",
                        help="comma list of element counts")
    parser.add_argument("--repeat", type=int, default=10)
    parser.add_argument("--num-parts", type=int, default=0,
                        help="values per push (0 = one per device for "
                             "local/tpu, 1 for dist)")
    args = parser.parse_args(argv)

    import jax
    import mxnet_tpu as mx

    kv = mx.kv.create(args.kv_store)
    n_parts = args.num_parts
    if n_parts <= 0:
        # device-resident stores push one value per device; dist stores
        # push one per worker process
        n_parts = 1 if "dist" in args.kv_store else len(jax.devices())

    print("kvstore=%s rank=%d/%d parts=%d"
          % (args.kv_store, kv.rank, kv.num_workers, n_parts),
          flush=True)
    for tok in args.sizes.split(","):
        size = int(float(tok))
        gbs = measure(kv, size, args.repeat, n_parts)
        print("size %12d elems  %8.2f MB   %7.3f GB/s (push+pull)"
              % (size, 4 * size / 1e6, gbs), flush=True)
    if "dist" in args.kv_store:
        kv.barrier()
        if kv.rank == 0:
            kv.stop_server()
    return 0


if __name__ == "__main__":
    sys.exit(main())
