"""graftsched core: a cooperative serializing scheduler.

One controlled thread runs at a time; every synchronization operation
(lock acquire/release, condition wait/notify, event set/wait, queue
put/get, thread start/join, tracked-attribute read/write, explicit
``san.sched_point()``) is a *yield point* where the scheduler decides
which thread proceeds.  The decision sequence is recorded so the
explorer (``tools.graftsched.explore``) can branch on it (iterative
preemption bounding + DPOR-lite pruning) and a failing run can be
replayed bit-deterministically from its serialized trace.

Design notes
------------
* Token passing: each thread has a control block (``_TCB``) with a real
  ``threading.Event`` gate.  A thread announces its pending op at a
  yield point, the scheduler picks a grantee (under one real mutex),
  and either the caller continues or it parks on its gate while the
  grantee's gate is set.
* Blocking ops carry a *pred* callable (e.g. "lock is free"); a thread
  is *enabled* when its pred is true.  Preds are re-evaluated at every
  pick, which is safe because no other controlled thread is running.
* Logical time: a timed waiter (``wait(timeout=...)``) is granted with
  reason ``"timeout"`` only when **no** untimed-enabled thread exists.
  Real clocks never gate progress, so schedules are deterministic.
* Deadlock: nothing enabled and no timed waiters => finding with every
  live thread's stack.  Livelock: more than ``max_steps`` decisions.
* Abort: ``_SchedAbort`` derives from ``BaseException`` so scenario
  code's ``except Exception`` blocks cannot swallow the teardown.

The scheduler is installed process-globally (``install``/``uninstall``)
but only threads it spawned are *controlled*; everything else —
including the explorer driving it — sees plain primitives via the
``mxnet_tpu.sanitizer`` gating.
"""

from __future__ import annotations

import os
import queue as _queue
import sys
import threading as _threading
import traceback as _traceback

__all__ = [
    "Scheduler", "SchedulerError", "install", "uninstall", "current",
    "current_controlled", "DEFAULT_MAX_STEPS",
]

DEFAULT_MAX_STEPS = int(os.environ.get("MXNET_SCHED_MAX_STEPS", "4000"))

# ops where two accesses to the same object are independent
_READ_KINDS = frozenset(["rd"])


class SchedulerError(RuntimeError):
    """Misuse of the scheduler or its primitives."""


class _SchedAbort(BaseException):
    """Raised inside controlled threads to unwind them at teardown.

    BaseException on purpose: scenario code's ``except Exception``
    recovery paths must not capture the scheduler's own abort.
    """


class _TCB(object):
    __slots__ = ("tid", "name", "thread", "gate", "op_kind", "op_key",
                 "pred", "timed", "wake_reason", "finished")

    def __init__(self, tid, name):
        self.tid = tid
        self.name = name
        self.thread = None          # real threading.Thread
        self.gate = _threading.Event()
        self.op_kind = None         # pending op, None while running
        self.op_key = None
        self.pred = None            # None => unconditionally enabled
        self.timed = False          # pending op carries a timeout
        self.wake_reason = None     # "run" | "timeout", set at grant
        self.finished = False


# -- module-level installation ------------------------------------------------

_INSTALLED = None


def install(sch):
    global _INSTALLED
    _INSTALLED = sch


def uninstall():
    global _INSTALLED
    _INSTALLED = None


def current():
    return _INSTALLED


def current_controlled():
    """The installed scheduler iff the *calling thread* is one of its
    controlled threads; None otherwise (the sanitizer bridge's gate)."""
    s = _INSTALLED
    if s is not None and s.controls_current():
        return s
    return None


class Scheduler(object):
    """One exploration/replay run: spawn a root thread, serialize every
    controlled thread through yield points, record the decisions."""

    def __init__(self, overrides=None, replay=None, max_steps=None,
                 wedge_timeout=30.0):
        self._mu = _threading.Lock()          # real: guards all state below
        self._tcbs = {}                       # tid -> _TCB
        self._idents = {}                     # real thread ident -> _TCB
        self._next_tid = 0
        self._obj_seq = 0
        self._decisions = []                  # [(tid, kind, key, reason)]
        self._enabled_others = []             # per step: [tid] untimed-enabled
        self._ops_by_tid = {}                 # tid -> [(step, kind, key)]
        self._overrides = dict(overrides or {})   # step -> forced tid
        self._replay = list(replay) if replay is not None else None
        self._max_steps = max_steps if max_steps else DEFAULT_MAX_STEPS
        self._wedge = wedge_timeout
        self._finding = None
        self._aborting = False
        self._done = _threading.Event()

    # -- identity ------------------------------------------------------------

    def controls_current(self):
        return _threading.get_ident() in self._idents

    def current_tid(self):
        return self._idents[_threading.get_ident()].tid

    def _self_tcb(self):
        return self._idents.get(_threading.get_ident())

    def _next_key(self, prefix):
        with self._mu:
            self._obj_seq += 1
            return "%s%d" % (prefix, self._obj_seq)

    # -- factories (called via mxnet_tpu.sanitizer) --------------------------

    def make_lock(self, label=None):
        return SchedLock(self, label)

    def make_rlock(self, label=None):
        return SchedRLock(self, label)

    def make_condition(self, lock=None, label=None):
        return SchedCondition(self, lock, label)

    def make_event(self):
        return SchedEvent(self)

    def make_queue(self, maxsize=0):
        return SchedQueue(self, maxsize)

    def make_thread(self, target=None, name=None, args=(), kwargs=None,
                    daemon=None):
        return SchedThread(self, target=target, name=name, args=args,
                           kwargs=kwargs or {}, daemon=daemon)

    def track_object(self, obj, attrs, label=None):
        return track_object(self, obj, attrs, label)

    def explicit_point(self, label=None):
        self._yield("point", "P.%s" % (label or "?"))

    # -- run lifecycle -------------------------------------------------------

    def run(self, fn, args=(), kwargs=None, name="root"):
        """Execute *fn* as controlled thread 0, schedule every spawned
        thread until all finish (or a finding aborts the run).  Returns
        the finding dict, or None on a clean run."""
        if self._tcbs:
            raise SchedulerError("Scheduler.run() is single-shot")
        tcb = self._new_tcb(name)
        tcb.op_kind, tcb.op_key = "th_entry", None
        real = _threading.Thread(
            target=self._bootstrap, args=(tcb, fn, args, kwargs or {}),
            name="graftsched-%s" % name, daemon=True)
        tcb.thread = real
        real.start()
        with self._mu:
            ok = self._grant_locked(tcb, "run")
            if ok:
                tcb.gate.set()
        if not self._done.wait(self._wedge * 4):
            with self._mu:
                if self._finding is None:
                    self._finding = self._mk_finding_locked(
                        "wedged", "run did not complete within %.0fs — a "
                        "controlled thread is blocked outside the "
                        "scheduler (real I/O?)" % (self._wedge * 4))
                self._abort_locked()
            self._done.wait(5.0)
        for t in list(self._tcbs.values()):
            if t.thread is not None:
                t.thread.join(2.0)
        return self._finding

    def result(self):
        return {
            "decisions": list(self._decisions),
            "enabled_others": [list(e) for e in self._enabled_others],
            "ops_by_tid": {t: list(o) for t, o in self._ops_by_tid.items()},
            "finding": self._finding,
        }

    def _new_tcb(self, name):
        with self._mu:
            tid = self._next_tid
            self._next_tid += 1
            tcb = _TCB(tid, name or ("thread-%d" % tid))
            self._tcbs[tid] = tcb
            self._ops_by_tid[tid] = []
            return tcb

    def _bootstrap(self, tcb, fn, args, kwargs):
        self._idents[_threading.get_ident()] = tcb
        tcb.gate.wait(self._wedge * 4)
        exc = None
        try:
            if not self._aborting:
                fn(*args, **kwargs)
        except _SchedAbort:
            pass
        except BaseException as e:          # noqa: BLE001 — becomes a finding
            exc = e
        self._finish(tcb, exc)

    def _finish(self, tcb, exc):
        with self._mu:
            tcb.finished = True
            tcb.op_kind = tcb.op_key = None
            tcb.pred = None
            if exc is not None and self._finding is None:
                tb = "".join(_traceback.format_exception(
                    type(exc), exc, exc.__traceback__))
                self._finding = {
                    "type": "exception",
                    "message": "thread %d (%s) raised %s: %s" % (
                        tcb.tid, tcb.name, type(exc).__name__, exc),
                    "step": len(self._decisions),
                    "stacks": [{"tid": tcb.tid, "name": tcb.name,
                                "stack": self._clean(tb.splitlines())}],
                }
                self._abort_locked()
            if all(t.finished for t in self._tcbs.values()):
                self._done.set()
                return
            if self._aborting:
                return
            pick = self._pick_locked(prefer=None)
            if pick is not None:
                nxt, reason = pick
                if self._grant_locked(nxt, reason):
                    nxt.gate.set()

    # -- the yield point -----------------------------------------------------

    def _yield(self, kind, key, pred=None, timeout=None):
        """Announce a pending op and block until granted.  Returns the
        grant reason ("run" or "timeout")."""
        tcb = self._self_tcb()
        if tcb is None:
            return "run"                    # uncontrolled: degrade
        if self._aborting:
            raise _SchedAbort()
        park = False
        with self._mu:
            if self._aborting:
                raise _SchedAbort()
            tcb.gate.clear()
            tcb.op_kind, tcb.op_key = kind, key
            tcb.pred = pred
            tcb.timed = timeout is not None
            tcb.wake_reason = None
            pick = self._pick_locked(prefer=tcb)
            if pick is None:                # deadlock/livelock: aborted
                raise _SchedAbort()
            nxt, reason = pick
            if not self._grant_locked(nxt, reason):
                raise _SchedAbort()
            if nxt is tcb:
                return tcb.wake_reason
            nxt.gate.set()
            park = True
        if park:
            self._park(tcb)
        if self._aborting:
            raise _SchedAbort()
        return tcb.wake_reason

    def _park(self, tcb):
        while not tcb.gate.wait(self._wedge):
            if self._aborting or tcb.gate.is_set():
                return
            with self._mu:
                if self._finding is None:
                    self._finding = self._mk_finding_locked(
                        "wedged", "thread %d (%s) parked past the wedge "
                        "timeout" % (tcb.tid, tcb.name))
                self._abort_locked()
            return

    def _enabled_locked(self, tcb):
        if tcb.finished or tcb.op_kind is None:
            return False
        if tcb.pred is None:
            return True
        try:
            return bool(tcb.pred())
        except Exception:
            return False

    def _pick_locked(self, prefer):
        """Choose the next grantee.  Returns (tcb, reason) or None after
        recording a deadlock finding and aborting."""
        step = len(self._decisions)
        enabled = [t for t in self._tcbs.values()
                   if self._enabled_locked(t)]
        enabled.sort(key=lambda t: t.tid)
        # replay: force the recorded tid at each step
        if self._replay is not None and step < len(self._replay):
            want_tid = self._replay[step][0]
            want = self._tcbs.get(want_tid)
            if want is not None and want in enabled:
                return want, "run"
            if want is not None and not want.finished and \
                    want.op_kind is not None and want.timed:
                return want, "timeout"
            if self._finding is None:
                self._finding = self._mk_finding_locked(
                    "divergence", "replay step %d wants thread %d but it "
                    "is not schedulable" % (step, want_tid))
            self._abort_locked()
            return None
        # exploration: a branch override forces a specific enabled thread
        forced = self._overrides.get(step)
        if forced is not None:
            for t in enabled:
                if t.tid == forced:
                    return t, "run"
            # state diverged from the parent run: fall through to default
        if prefer is not None and prefer in enabled:
            return prefer, "run"
        if enabled:
            return enabled[0], "run"
        timed = sorted((t for t in self._tcbs.values()
                        if not t.finished and t.op_kind is not None
                        and t.timed), key=lambda t: t.tid)
        if timed:
            return timed[0], "timeout"
        live = [t for t in self._tcbs.values() if not t.finished]
        if live and self._finding is None:
            self._finding = self._mk_finding_locked(
                "deadlock", "all %d live threads blocked: %s" % (
                    len(live), ", ".join(
                        "%d(%s) on %s %s" % (t.tid, t.name, t.op_kind,
                                             t.op_key)
                        for t in sorted(live, key=lambda t: t.tid))))
        self._abort_locked()
        return None

    def _grant_locked(self, tcb, reason):
        """Record the decision and hand the token to *tcb*.  Returns
        False when the step budget trips (livelock guard)."""
        step = len(self._decisions)
        if step >= self._max_steps:
            if self._finding is None:
                self._finding = self._mk_finding_locked(
                    "livelock", "schedule exceeded %d steps without "
                    "terminating (livelock bound)" % self._max_steps)
            self._abort_locked()
            return False
        decision = (tcb.tid, tcb.op_kind, tcb.op_key, reason)
        if self._replay is not None and step < len(self._replay):
            exp = tuple(self._replay[step])
            if tuple(decision) != exp:
                self._finding = self._mk_finding_locked(
                    "divergence", "replay step %d recorded %r but run "
                    "produced %r" % (step, exp, decision))
                self._abort_locked()
                return False
        self._decisions.append(decision)
        self._enabled_others.append(
            [t.tid for t in self._tcbs.values()
             if t is not tcb and self._enabled_locked(t)])
        self._ops_by_tid[tcb.tid].append((step, tcb.op_kind, tcb.op_key))
        tcb.wake_reason = reason
        tcb.op_kind = tcb.op_key = None
        tcb.pred = None
        tcb.timed = False
        return True

    # -- findings ------------------------------------------------------------

    def _abort_locked(self):
        self._aborting = True
        for t in self._tcbs.values():
            if not t.finished:
                t.gate.set()
        # if every thread already finished the run is over
        if all(t.finished for t in self._tcbs.values()):
            self._done.set()

    @staticmethod
    def _clean(lines):
        """Drop scheduler-internal frames (a File line plus its source
        echo) so reports show scenario code, not graftsched plumbing."""
        drop = (os.sep + "graftsched" + os.sep, "sanitizer.py",
                os.sep + "threading.py")
        kept = []
        skip = False
        for ln in lines:
            if ln.lstrip().startswith('File "'):
                skip = any(d in ln for d in drop)
            if not skip:
                kept.append(ln)
        return kept or lines

    def _mk_finding_locked(self, kind, message):
        frames = sys._current_frames()
        me = _threading.get_ident()
        stacks = []
        for t in sorted(self._tcbs.values(), key=lambda t: t.tid):
            if t.finished or t.thread is None:
                continue
            if t.thread.ident == me:
                stack = _traceback.format_stack()
            else:
                fr = frames.get(t.thread.ident)
                stack = _traceback.format_stack(fr) if fr is not None \
                    else ["<thread not started>"]
            flat = []
            for s in stack:
                flat.extend(s.rstrip("\n").splitlines())
            stacks.append({"tid": t.tid, "name": t.name,
                           "stack": self._clean(flat)})
        return {"type": kind, "message": message,
                "step": len(self._decisions), "stacks": stacks}


# -- controlled primitives ----------------------------------------------------

class _SchedBase(object):
    """Shared inactive-degradation: when the owning scheduler is no
    longer installed or the calling thread is not controlled (e.g. the
    scenario ``check()`` phase), ops run against the logical state with
    no yields and no blocking."""

    def _active(self):
        return _INSTALLED is self._sch and self._sch.controls_current()


class SchedLock(_SchedBase):
    def __init__(self, sch, label=None):
        self._sch = sch
        self.key = sch._next_key("L")
        self.label = label
        self._owner = None                  # tid, or -1 when inactive-held

    def acquire(self, blocking=True, timeout=-1):
        if not self._active():
            self._owner = -1
            return True
        sch = self._sch
        if not blocking:
            sch._yield("lk_try", self.key)
            if self._owner is None:
                self._owner = sch.current_tid()
                return True
            return False
        tmo = None if timeout is None or timeout < 0 else timeout
        reason = sch._yield("lk_acq", self.key,
                            pred=lambda: self._owner is None, timeout=tmo)
        if reason == "timeout":
            return False
        self._owner = sch.current_tid()
        return True

    def release(self):
        if not self._active():
            self._owner = None
            return
        sch = self._sch
        if self._owner != sch.current_tid():
            raise SchedulerError("release of %s not held by tid %d"
                                 % (self.key, sch.current_tid()))
        sch._yield("lk_rel", self.key)
        self._owner = None

    def locked(self):
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # condition support
    def _free(self):
        return self._owner is None

    def _held_by(self, tid):
        return self._owner == tid

    def _cond_release_save(self):
        self._owner = None
        return 1

    def _cond_restore(self, saved, tid):
        self._owner = tid


class SchedRLock(_SchedBase):
    def __init__(self, sch, label=None):
        self._sch = sch
        self.key = sch._next_key("R")
        self.label = label
        self._owner = None
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        if not self._active():
            self._owner = -1
            self._count += 1
            return True
        sch = self._sch
        me = sch.current_tid()
        if self._owner == me:
            sch._yield("lk_acq", self.key)
            self._count += 1
            return True
        if not blocking:
            sch._yield("lk_try", self.key)
            if self._owner is None:
                self._owner, self._count = me, 1
                return True
            return False
        tmo = None if timeout is None or timeout < 0 else timeout
        reason = sch._yield(
            "lk_acq", self.key,
            pred=lambda: self._owner is None or self._owner == me,
            timeout=tmo)
        if reason == "timeout":
            return False
        self._owner, self._count = me, self._count + 1
        return True

    def release(self):
        if not self._active():
            self._count = max(0, self._count - 1)
            if self._count == 0:
                self._owner = None
            return
        sch = self._sch
        if self._owner != sch.current_tid():
            raise SchedulerError("release of %s not held by tid %d"
                                 % (self.key, sch.current_tid()))
        sch._yield("lk_rel", self.key)
        self._count -= 1
        if self._count == 0:
            self._owner = None

    def locked(self):
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _free(self):
        return self._owner is None

    def _held_by(self, tid):
        return self._owner == tid

    def _cond_release_save(self):
        saved = self._count
        self._owner, self._count = None, 0
        return saved

    def _cond_restore(self, saved, tid):
        self._owner, self._count = tid, saved


class SchedCondition(_SchedBase):
    def __init__(self, sch, lock=None, label=None):
        self._sch = sch
        self.key = sch._next_key("C")
        self.label = label
        if lock is None:
            lock = SchedRLock(sch, label)
        elif not isinstance(lock, (SchedLock, SchedRLock)):
            raise SchedulerError(
                "SchedCondition needs a scheduler-controlled lock; got %r"
                % (lock,))
        self._lock = lock
        self._waiting = []                  # FIFO of waiting tids
        self._notified = set()

    # delegate the lock protocol
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout=None):
        if not self._active():
            return True                     # single-threaded check phase
        sch = self._sch
        me = sch.current_tid()
        if not self._lock._held_by(me):
            raise SchedulerError("cond %s wait() without the lock"
                                 % self.key)
        saved = self._lock._cond_release_save()
        self._waiting.append(me)
        reason = sch._yield(
            "cond_wait", self.key,
            pred=lambda: me in self._notified and self._lock._free(),
            timeout=timeout)
        if reason == "timeout":
            try:
                self._waiting.remove(me)
            except ValueError:
                pass
            if me in self._notified:
                # the wakeup arrived while the lock was still held:
                # hand it to the next waiter instead of losing it
                self._notified.discard(me)
                if self._waiting:
                    self._notified.add(self._waiting[0])
            sch._yield("cond_reacq", self.key,
                       pred=self._lock._free)
            self._lock._cond_restore(saved, me)
            return False
        self._notified.discard(me)
        try:
            self._waiting.remove(me)
        except ValueError:
            pass
        self._lock._cond_restore(saved, me)
        return True

    def wait_for(self, predicate, timeout=None):
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n=1):
        if not self._active():
            return
        sch = self._sch
        if not self._lock._held_by(sch.current_tid()):
            raise SchedulerError("cond %s notify() without the lock"
                                 % self.key)
        sch._yield("cond_notify", self.key)
        for tid in self._waiting:
            if n <= 0:
                break
            if tid not in self._notified:
                self._notified.add(tid)
                n -= 1

    def notify_all(self):
        if not self._active():
            return
        sch = self._sch
        if not self._lock._held_by(sch.current_tid()):
            raise SchedulerError("cond %s notify_all() without the lock"
                                 % self.key)
        sch._yield("cond_nall", self.key)
        self._notified.update(self._waiting)


class SchedEvent(_SchedBase):
    def __init__(self, sch):
        self._sch = sch
        self.key = sch._next_key("E")
        self._flag = False

    def set(self):
        if self._active():
            self._sch._yield("ev_set", self.key)
        self._flag = True

    def clear(self):
        if self._active():
            self._sch._yield("ev_clear", self.key)
        self._flag = False

    def is_set(self):
        return self._flag

    def wait(self, timeout=None):
        if not self._active():
            return self._flag
        reason = self._sch._yield("ev_wait", self.key,
                                  pred=lambda: self._flag,
                                  timeout=timeout)
        if reason == "timeout":
            return self._flag
        return True


class SchedQueue(_SchedBase):
    def __init__(self, sch, maxsize=0):
        self._sch = sch
        self.key = sch._next_key("Q")
        self.maxsize = maxsize
        self._items = []

    def _room(self):
        return self.maxsize <= 0 or len(self._items) < self.maxsize

    def put(self, item, block=True, timeout=None):
        if not self._active():
            self._items.append(item)
            return
        if not block:
            self._sch._yield("q_put", self.key)
            if not self._room():
                raise _queue.Full()
            self._items.append(item)
            return
        reason = self._sch._yield("q_put", self.key, pred=self._room,
                                  timeout=timeout)
        if reason == "timeout":
            raise _queue.Full()
        self._items.append(item)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block=True, timeout=None):
        if not self._active():
            if not self._items:
                raise _queue.Empty()
            return self._items.pop(0)
        if not block:
            self._sch._yield("q_get", self.key)
            if not self._items:
                raise _queue.Empty()
            return self._items.pop(0)
        reason = self._sch._yield("q_get", self.key,
                                  pred=lambda: len(self._items) > 0,
                                  timeout=timeout)
        if reason == "timeout":
            raise _queue.Empty()
        return self._items.pop(0)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self):
        return len(self._items)

    def empty(self):
        return not self._items

    def full(self):
        return not self._room()


class SchedThread(_SchedBase):
    """Controlled thread handle mirroring threading.Thread's surface."""

    def __init__(self, sch, target=None, name=None, args=(), kwargs=None,
                 daemon=None):
        self._sch = sch
        self.key = sch._next_key("T")
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.name = name or "sched-%s" % self.key
        self.daemon = True if daemon is None else daemon
        self._tcb = None
        self._plain = None

    def start(self):
        if self._tcb is not None or self._plain is not None:
            raise SchedulerError("thread %s started twice" % self.key)
        if not self._active():
            self._plain = _threading.Thread(  # graftlint: disable=JG011
                target=self._target, name=self.name, args=self._args,
                kwargs=self._kwargs, daemon=self.daemon)
            self._plain.start()
            return
        sch = self._sch
        sch._yield("th_start", self.key)
        tcb = sch._new_tcb(self.name)
        tcb.op_kind, tcb.op_key = "th_entry", self.key
        real = _threading.Thread(
            target=sch._bootstrap,
            args=(tcb, self._target, self._args, self._kwargs),
            name="graftsched-%s" % self.name, daemon=True)
        tcb.thread = real
        self._tcb = tcb
        real.start()

    def join(self, timeout=None):
        if self._plain is not None:
            self._plain.join(timeout)
            return
        if self._tcb is None:
            raise SchedulerError("join of %s before start" % self.key)
        if not self._active():
            if self._tcb.thread is not None:
                self._tcb.thread.join(timeout if timeout is not None
                                      else 2.0)
            return
        tcb = self._tcb
        self._sch._yield("th_join", self.key,
                         pred=lambda: tcb.finished, timeout=timeout)

    def is_alive(self):
        if self._plain is not None:
            return self._plain.is_alive()
        if self._tcb is None:
            return False
        return not self._tcb.finished

    @property
    def ident(self):
        if self._plain is not None:
            return self._plain.ident
        return self._tcb.thread.ident if self._tcb is not None else None


# -- tracked shared objects ---------------------------------------------------

_TRACKED_CACHE = {}


def _tracked_class(base, sch_ref_unused=None):
    cached = _TRACKED_CACHE.get(base)
    if cached is not None:
        return cached

    class Tracked(base):
        __doc__ = base.__doc__

        def __getattribute__(self, name):
            d = object.__getattribute__(self, "__dict__")
            attrs = d.get("_graftsched_attrs")
            if attrs is not None and name in attrs:
                sch = d.get("_graftsched_sch")
                if sch is not None and _INSTALLED is sch and \
                        sch.controls_current():
                    sch._yield("rd", "%s.%s"
                               % (d.get("_graftsched_key"), name))
            return object.__getattribute__(self, name)

        def __setattr__(self, name, value):
            d = object.__getattribute__(self, "__dict__")
            attrs = d.get("_graftsched_attrs")
            if attrs is not None and name in attrs:
                sch = d.get("_graftsched_sch")
                if sch is not None and _INSTALLED is sch and \
                        sch.controls_current():
                    sch._yield("wr", "%s.%s"
                               % (d.get("_graftsched_key"), name))
            object.__setattr__(self, name, value)

    Tracked.__name__ = base.__name__
    Tracked.__qualname__ = base.__qualname__
    _TRACKED_CACHE[base] = Tracked
    return Tracked


def track_object(sch, obj, attrs, label=None):
    """Swap *obj*'s class for a subclass whose tracked attribute
    accesses are yield points (mirrors graftsan's lockset tracker)."""
    base = type(obj)
    if getattr(base, "__getattribute__", None) is not \
            object.__getattribute__ and \
            object.__getattribute__(obj, "__dict__").get(
                "_graftsched_attrs") is not None:
        # already tracked: widen the attr set
        d = object.__getattribute__(obj, "__dict__")
        d["_graftsched_attrs"] = frozenset(d["_graftsched_attrs"]) \
            | frozenset(attrs)
        return obj
    cls = _tracked_class(base)
    key = sch._next_key("O")
    d = object.__getattribute__(obj, "__dict__")
    d["_graftsched_attrs"] = frozenset(attrs)
    d["_graftsched_key"] = key
    d["_graftsched_sch"] = sch
    d["_graftsched_label"] = label or base.__name__
    obj.__class__ = cls
    return obj
