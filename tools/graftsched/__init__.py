"""graftsched — deterministic schedule-exploration checker.

A CHESS-style cooperative scheduler (iterative preemption bounding,
DPOR-lite pruning) that commandeers the ``mxnet_tpu.sanitizer``
primitive factories under ``MXNET_SAN=sched`` and drives the threaded
serving/kvstore subsystems through bounded interleavings, replaying
any failing schedule bit-deterministically from a JSON trace.

Entry points: ``python -m tools.graftsched`` (CLI), ``ci/sched_drill.py``
(CI stage), ``tools.graftsched.explore`` (library).
"""

from __future__ import annotations

try:
    from mxnet_tpu.observability import metrics as _metrics
    SCHEDULES_TOTAL = _metrics.counter(
        "graftsched_schedules_total",
        help="schedules executed by the graftsched explorer")
    FINDINGS_TOTAL = _metrics.counter(
        "graftsched_findings_total",
        help="failing interleavings found (deadlock/livelock/exception/"
             "invariant/divergence)")
except Exception:  # pragma: no cover - standalone checkout use
    SCHEDULES_TOTAL = None
    FINDINGS_TOTAL = None

from . import core  # noqa: E402,F401
