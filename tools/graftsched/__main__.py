"""graftsched CLI — explore scenarios or replay a recorded trace.

Usage::

    python -m tools.graftsched --list
    python -m tools.graftsched [scenario ...] [--budget N]
                               [--preemptions N] [--trace-dir DIR]
    python -m tools.graftsched --replay TRACE.json

Exit status: 0 when every explored scenario is finding-free (or the
replay reproduced no finding), 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="graftsched",
        description="deterministic schedule-exploration checker")
    ap.add_argument("scenarios", nargs="*",
                    help="scenario names (default: all shipped)")
    ap.add_argument("--list", action="store_true",
                    help="list registered scenarios and exit")
    ap.add_argument("--budget", type=int, default=None,
                    help="max schedules per scenario")
    ap.add_argument("--preemptions", type=int, default=None,
                    help="preemption bound (default 2)")
    ap.add_argument("--trace-dir", default=None,
                    help="where failing traces are written")
    ap.add_argument("--replay", metavar="TRACE",
                    help="re-execute a recorded trace and exit")
    args = ap.parse_args(argv)

    # self-contained: the factories only reroute under MXNET_SAN=sched
    san = os.environ.get("MXNET_SAN", "")
    if "sched" not in san and san != "all":
        os.environ["MXNET_SAN"] = (san + ",sched").lstrip(",")

    from . import explore, scenarios

    if args.list:
        for name in scenarios.names():
            print(name)
        for name in sorted(scenarios.SEEDED):
            print("%s (seeded)" % name)
        return 0

    if args.replay:
        trace = explore.load_trace(args.replay)
        cls = scenarios.get(trace["scenario"])
        res = explore.replay(cls, trace)
        finding = res["finding"]
        recorded = [tuple(d) for d in trace["decisions"]]
        diverged = list(res["decisions"]) != recorded
        if finding is None and not diverged:
            print("graftsched replay: %s — no finding (trace is "
                  "stale or the bug is fixed)" % trace["scenario"])
            return 0
        print("graftsched replay: %s — %s" % (
            trace["scenario"],
            "DIVERGED from the recording" if diverged
            else finding["type"]))
        if finding is not None:
            print(finding["message"])
        return 1

    names = args.scenarios or scenarios.names()
    rc = 0
    for name in names:
        cls = scenarios.get(name)
        res = explore.explore(cls, budget=args.budget,
                              max_preemptions=args.preemptions,
                              trace_dir=args.trace_dir)
        finding = res["finding"]
        if finding is None:
            print("graftsched: %s schedules=%d ok"
                  % (name, res["schedules"]))
        else:
            rc = 1
            print("graftsched: %s schedules=%d FINDING=%s trace=%s"
                  % (name, res["schedules"], finding["type"],
                     res["trace_path"]))
            print(finding["message"])
    return rc


if __name__ == "__main__":
    sys.exit(main())
