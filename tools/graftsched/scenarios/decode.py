"""DecodeBatcher scenario: join / cancel / crash-rebuild / flush / close.

A fake engine (numpy-free token counter — no XLA dispatch, no pool)
feeds the real DecodeBatcher tick loop.  The first ``tick`` raises, so
every explored schedule also drives the quarantine/rebuild path with a
budget of one; two client threads race joins and a cancel against the
crash.  Invariants after every schedule:

* the never-cancelled session finishes "complete" with all its tokens
* the cancelled session resolves typed (or its admission shed typed
  while the rebuild was in flight)
* exactly one rebuild happened, and every admitted session was
  released exactly once (the engine-side release is idempotent by
  contract; the fake counts effective releases)
* flush() and close() report clean, the batcher leaves the engine's
  registry, and no session lingers in joins/sessions/inflight
"""

from __future__ import annotations


class _FakeSession:
    _next_sid = [0]

    def __init__(self, san, max_new):
        self.sid = _FakeSession._next_sid[0]
        _FakeSession._next_sid[0] += 1
        self.cancelled = False
        self._deadline = None
        self._t_enq = 0.0
        self.max_new = max_new
        self.tokens = 0
        self.prefills = 0
        self._done = False
        self._released = False
        self.error = None
        self.finish_reason = None
        self.done_ev = san.event()
        san.track(self, ("cancelled", "tokens", "_done", "_released"),
                  label="sess%d" % self.sid)

    def done(self):
        return self._done


class _FakeEngine:
    """The DecodeBatcher-facing slice of DecodeEngine: admit/prefill/
    tick/readmit/release/rebuild_pool over plain counters.  The first
    tick crashes (seeded) so the rebuild path runs every schedule."""

    class _Ladder:
        max_batch = 4

    def __init__(self, san):
        self._san = san
        self.label = "sched-decode"
        self.ladder = self._Ladder()
        self._lock = san.lock(label="fake-engine")
        self._batchers = []
        self.compile_count = 0
        self.sessions = []
        self.releases = []
        self.rebuilds = 0
        self.crash_armed = True
        san.track(self, ("sessions", "releases", "rebuilds",
                         "crash_armed"), label="fake-engine")

    def admit(self, prompt, max_new_tokens=None, stop_fn=None,
              deadline_ms=None, journal_key=None, incarnation=0,
              resume_tokens=None):
        sess = _FakeSession(self._san, max_new_tokens or 1)
        with self._lock:
            self.sessions = self.sessions + [sess]
        return sess

    def prefill(self, sess):
        with self._lock:
            sess.prefills += 1

    def tick(self, sessions):
        with self._lock:
            if self.crash_armed:
                self.crash_armed = False
                raise RuntimeError("seeded tick crash")
        for s in sessions:
            if s.done():
                continue
            if s.cancelled:
                from mxnet_tpu.serve.batcher import RequestCancelled
                self.release(s, "cancelled", RequestCancelled(
                    "decode session %d cancelled" % s.sid))
                continue
            with self._lock:
                s.tokens += 1
                finished = s.tokens >= s.max_new
            if finished:
                self.release(s, "complete", None)

    def readmit(self, sess):
        with self._lock:
            if sess.done():
                return sess
            sess._deadline = None
        return sess

    def rebuild_pool(self):
        with self._lock:
            self.rebuilds += 1
            # a fresh pool: the seeded fault does not recur
            self.crash_armed = False

    def release(self, sess, reason, error=None):
        with self._lock:
            if sess._released:
                return
            sess._released = True
            self.releases = self.releases + [(sess.sid, reason)]
            sess._done = True
            sess.error = error
            sess.finish_reason = reason
        sess.done_ev.set()


class DecodeScenario:
    name = "decode"
    budget = 80

    def run(self):
        from mxnet_tpu import sanitizer as _san
        from mxnet_tpu.serve.decode import DecodeBatcher

        eng = _FakeEngine(_san)
        b = DecodeBatcher(eng, max_wait_ms=0, name="sched-decode",
                          rebuilds=1)
        state = {"engine": eng, "batcher": b, "outcomes": {}}

        def client_keep():
            s = b.start("hello", max_new_tokens=2)
            s.done_ev.wait()
            state["outcomes"]["keep"] = (s.finish_reason,
                                         type(s.error).__name__
                                         if s.error else None,
                                         s.tokens)

        def client_cancel():
            try:
                s = b.start("world", max_new_tokens=4)
            except Exception as exc:
                # admission shed typed while rebuilding/draining
                state["outcomes"]["cancel"] = ("shed",
                                               type(exc).__name__,
                                               0)
                return
            s.cancelled = True
            s.done_ev.wait()
            state["outcomes"]["cancel"] = (s.finish_reason,
                                           type(s.error).__name__
                                           if s.error else None,
                                           s.tokens)

        t1 = _san.thread(target=client_keep, name="keep")
        t2 = _san.thread(target=client_cancel, name="cancel")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        state["flushed"] = b.flush(timeout=30.0)
        state["closed"] = b.close(timeout=30.0)
        state["rebuilds"] = b.rebuild_count
        return state

    def check(self, state):
        eng = state["engine"]
        b = state["batcher"]
        out = state["outcomes"]
        assert set(out) == {"keep", "cancel"}, out
        reason, err, tokens = out["keep"]
        assert reason == "complete" and err is None and tokens == 2, \
            out
        reason, err, tokens = out["cancel"]
        if reason == "shed":
            assert err == "ServeError", out
        else:
            # the cancel either lost the race (session completed) or
            # resolved typed
            assert (reason, err) in (
                ("cancelled", "RequestCancelled"),
                ("complete", None)), out
        assert state["flushed"] is True, state
        assert state["closed"] is True, state
        assert state["rebuilds"] == 1, state["rebuilds"]
        assert eng.rebuilds == 1, eng.rebuilds
        # exactly-once release per admitted session
        sids = [sid for sid, _ in eng.releases]
        assert len(sids) == len(set(sids)), eng.releases
        assert len(sids) == len(eng.sessions), (eng.releases,
                                                len(eng.sessions))
        for s in eng.sessions:
            assert s._released and s._done, s.sid
        assert eng._batchers == [], eng._batchers
        assert not b._joins and not b._sessions, (b._joins,
                                                  b._sessions)
        assert b._inflight == (), b._inflight
