"""KVStoreServer scenario: SET_OPT racing an owner push + its retry.

One dist_async server, one key initialised by the root.  Three
threads race through ``_handle`` (no sockets — the handler IS the
subject): a SET_OPT installing the SGD updater, an owner PUSH and a
duplicate PUSH with the same request id.  A push that beats SET_OPT
fails typed (async pushes require the server-side updater) and leaves
the dedup window, so its retry re-executes.  Invariants:

* at most one apply ever commits (exactly-once through the window)
* the stored value proves it: ``1 - lr * applies`` — a double apply
  would show ``1 - 2*lr``
* a dup-flagged ok reply implies an owner ok reply, and applies
  equals the count of non-dup ok replies
* dispatch accounting: 1 <= pushes_received <= 2, never below applies
"""

from __future__ import annotations

import pickle

import numpy as _np

_LR = 0.1


class KVServerScenario:
    name = "kvserver"
    budget = 80

    def run(self):
        from mxnet_tpu import sanitizer as _san
        from mxnet_tpu._kvstore_impl import (_MSG_INIT, _MSG_PUSH,
                                             _MSG_SET_OPT,
                                             KVStoreServer)
        from mxnet_tpu.base import MXNetError
        from mxnet_tpu.optimizer.optimizer import SGD

        server = KVStoreServer(False, 1)
        server._dispatch(_MSG_INIT, {"key": "w"},
                         [_np.ones((2,), _np.float32)])
        blob = _np.frombuffer(pickle.dumps(SGD(learning_rate=_LR)),
                              _np.uint8)
        state = {"server": server, "outcomes": {}}

        def set_opt():
            server._handle(_MSG_SET_OPT, {"req": (0, 10, 0)}, [blob])

        def push(key):
            try:
                rmeta, _ = server._handle(
                    _MSG_PUSH, {"req": (1, 1, 0), "key": "w"},
                    [_np.ones((2,), _np.float32)])
                state["outcomes"][key] = ("ok",
                                          bool(rmeta.get("dup")))
            except MXNetError:
                state["outcomes"][key] = ("err", None)

        threads = [_san.thread(target=set_opt, name="set-opt"),
                   _san.thread(target=push, args=("p1",),
                               name="push-owner"),
                   _san.thread(target=push, args=("p2",),
                               name="push-dup")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        state["value"] = server.store["w"].asnumpy()
        return state

    def check(self, state):
        server = state["server"]
        out = state["outcomes"]
        try:
            assert set(out) == {"p1", "p2"}, out
            oks = [k for k in out if out[k][0] == "ok"]
            owner_oks = [k for k in oks if not out[k][1]]
            dup_oks = [k for k in oks if out[k][1]]
            assert server.applies in (0, 1), server.applies
            assert server.applies == len(owner_oks), (server.applies,
                                                      out)
            if dup_oks:
                assert owner_oks, out
            assert 1 <= server.pushes_received <= 2, \
                server.pushes_received
            assert server.pushes_received >= server.applies
            assert server.updater is not None
            expected = 1.0 - _LR * server.applies
            assert _np.allclose(state["value"], expected), \
                (state["value"], expected, server.applies)
        finally:
            try:
                server.sock.close()
            except OSError:
                pass
