"""DynamicBatcher scenario: submit / cancel / drain / close.

Two submitter threads race the dispatcher and a cancel; the root then
drains and closes.  Invariants checked after every schedule:

* every future resolved (result or typed error) — none left pending
* a successful cancel() implies a RequestCancelled resolution
* queue accounting returns to zero (rows/bytes/pending/inflight)
* close() reports clean (the dispatcher joined)
"""

from __future__ import annotations

import numpy as _np


class _Out:
    def __init__(self, data):
        self._data = data


class _Ladder:
    def __init__(self, top):
        self.max_batch = top

    def batch_for(self, rows):
        return rows


class _FakePredictor:
    """The DynamicBatcher-facing surface of CompiledPredictor, with
    the XLA boundary replaced by numpy (a controlled thread must
    never block in a real device dispatch)."""

    def __init__(self):
        self.name = "sched-batcher"
        self._data_shapes = {"data": (1, 2)}
        self._bucket_inputs = {"data"}
        self.ladder = _Ladder(4)
        self.tuning = None

    def predict(self, feed):
        rows = int(feed["data"].shape[0])
        return [_Out(_np.full((rows, 2), 7.0, _np.float32))]


class BatcherScenario:
    name = "batcher"
    budget = 80

    def run(self):
        from mxnet_tpu import sanitizer as _san
        from mxnet_tpu.serve.batcher import DynamicBatcher

        b = DynamicBatcher(_FakePredictor(), max_wait_ms=0, max_batch=0,
                           max_queue=0, max_queue_bytes=0,
                           default_deadline_ms=0, max_restarts=0,
                           tuning={})
        state = {"batcher": b, "outcomes": {}}

        def submit_and_wait(key):
            fut = b.submit(_np.ones((1, 2), _np.float32))
            try:
                res = fut.result(None)
                state["outcomes"][key] = ("ok", res[0].shape)
            except Exception as exc:  # typed shed/cancel — recorded
                state["outcomes"][key] = ("err", type(exc).__name__)

        def submit_and_cancel(key):
            fut = b.submit(_np.ones((1, 2), _np.float32))
            reclaimed = fut.cancel()
            try:
                res = fut.result(None)
                state["outcomes"][key] = ("ok", res[0].shape, reclaimed)
            except Exception as exc:
                state["outcomes"][key] = ("err", type(exc).__name__,
                                          reclaimed)

        t1 = _san.thread(target=submit_and_wait, args=("s1",),
                         name="submit")
        t2 = _san.thread(target=submit_and_cancel, args=("s2",),
                         name="cancel")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        state["drained"] = b.drain(timeout=30.0)
        state["closed_clean"] = b.close(timeout=30.0)
        return state

    def check(self, state):
        b = state["batcher"]
        out = state["outcomes"]
        assert set(out) == {"s1", "s2"}, out
        # s1 never cancels: it must land (the drain waits for it)
        assert out["s1"][0] == "ok", out
        assert out["s1"][1] == (1, 2), out
        # s2: a successful cancel implies the typed cancelled error;
        # a failed cancel means the request dispatched and resolved ok
        kind = out["s2"][0]
        reclaimed = out["s2"][2]
        if reclaimed:
            assert kind == "err" and out["s2"][1] == "RequestCancelled", \
                out
        else:
            assert kind == "ok", out
        assert state["drained"] is True, state
        assert state["closed_clean"] is True, state
        assert b._rows_pending == 0, b._rows_pending
        assert b._bytes_pending == 0, b._bytes_pending
        assert not b._pending, b._pending
        assert b._inflight == (), b._inflight
