"""ReplicaServer scenarios: the idempotency window and stop().

``ReplicaScenario`` races an owner predict, a duplicate of the same
request id, a CANCEL for that id and two concurrent ``stop()`` calls
over a fake registry (no XLA, no accept loop).  Invariants:

* owner and duplicate replies are identical modulo the ``dup`` flag
  (the ``_publish`` exactly-once contract)
* at most one dispatch reached the registry
* the probe http server is shut down exactly once and the listen
  socket is closed — two racing stop() calls must not double-teardown

``SeededReplicaTeardown`` re-introduces the PR-19 ``stop()``
double-teardown (check-then-act on ``self.http_server`` instead of
swap-then-close) in a subclass: the explorer must find the race —
either the NoneType crash or the double-shutdown invariant — within
budget, and the trace must replay to the same failure.  It is the
drill's teeth check and is not part of the shipped zero-findings set.
"""

from __future__ import annotations

import numpy as _np


class _FakeFuture:
    """ServeFuture's replica-facing surface: result()/cancel() with
    the compute faked at result() time so a cancel can win the
    race before the 'dispatch' lands."""

    def __init__(self, san, registry):
        self._registry = registry
        self._lock = san.lock(label="fake-future")
        self._cancelled = False
        self._done = False
        san.track(self, ("_cancelled", "_done"), label="fake-future")

    def result(self, timeout=None):
        from mxnet_tpu.serve.batcher import RequestCancelled
        with self._lock:
            if self._cancelled:
                raise RequestCancelled("cancelled before dispatch")
            self._done = True
        self._registry.computes += 1
        return [_np.full((1, 2), 3.0, _np.float32)]

    def cancel(self):
        with self._lock:
            if self._done:
                return False
            self._cancelled = True
            return True


class _FakeRegistry:
    """ModelRegistry's submit surface over _FakeFuture."""

    def __init__(self, san):
        self._san = san
        self.submits = 0
        self.computes = 0
        san.track(self, ("submits", "computes"), label="fake-registry")

    def submit(self, model, data, deadline_ms=None):
        self.submits += 1
        return _FakeFuture(self._san, self)

    def close(self):
        pass


class _FakeHttp:
    """The two teardown calls stop() makes, as counters."""

    def __init__(self):
        self.shutdowns = 0
        self.closes = 0
        self.server_address = ("127.0.0.1", 0)

    def shutdown(self):
        self.shutdowns += 1

    def server_close(self):
        self.closes += 1


def _build_server(cls_name=None):
    import os
    from mxnet_tpu import sanitizer as _san
    from mxnet_tpu.serve import replica as _replica

    os.environ.setdefault("MXNET_SERVE_HTTP_PORT", "0")
    cls = _replica.ReplicaServer if cls_name is None else cls_name
    server = cls(registry=_FakeRegistry(_san), port=0,
                 name="sched-replica")
    http = _FakeHttp()
    server.http_server = http
    # widen the server's tracked set: stop() races on this attribute
    _san.track(server, ("http_server",), label="sched-replica-http")
    return server, http


class ReplicaScenario:
    name = "replica"
    budget = 96

    def run(self):
        from mxnet_tpu import sanitizer as _san

        server, http = _build_server()
        state = {"server": server, "http": http, "outcomes": {}}
        meta = {"req": ("c", 1, 0), "model": "m"}
        payload = [_np.ones((1, 2), _np.float32)]

        def predict(key):
            try:
                rmeta, rts = server._handle_predict(dict(meta),
                                                    list(payload))
                state["outcomes"][key] = ("reply", dict(rmeta),
                                          len(rts))
            except Exception as exc:
                state["outcomes"][key] = ("raise",
                                          type(exc).__name__, 0)

        def cancel():
            rmeta, _ = server._handle_cancel({"req": ("c", 1, 0)})
            state["outcomes"]["cancel"] = ("reply", dict(rmeta), 0)

        threads = [
            _san.thread(target=predict, args=("p1",), name="owner"),
            _san.thread(target=predict, args=("p2",), name="dup"),
            _san.thread(target=cancel, name="cancel"),
            _san.thread(target=server.stop, name="stop1"),
            _san.thread(target=server.stop, name="stop2"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return state

    def check(self, state):
        server = state["server"]
        http = state["http"]
        out = state["outcomes"]
        assert set(out) == {"p1", "p2", "cancel"}, out
        # both predict replies must tell one story modulo the dup flag
        replies = []
        for key in ("p1", "p2"):
            kind, rmeta, nts = out[key]
            assert kind == "reply", out
            rmeta = dict(rmeta)
            rmeta.pop("dup", None)
            replies.append((tuple(sorted(rmeta.items())), nts))
        assert replies[0] == replies[1], out
        # exactly-once dispatch per id
        assert server.predicts_dispatched <= 1, \
            server.predicts_dispatched
        assert server.registry.computes <= 1, server.registry.computes
        assert server.requests_received == 2, server.requests_received
        assert server.cancels_received == 1, server.cancels_received
        assert server.dup_hits in (1, 2), server.dup_hits
        # stop() ran twice but tore down once
        assert http.shutdowns == 1, http.shutdowns
        assert http.closes == 1, http.closes
        assert server.http_server is None, server.http_server
        assert server.sock.fileno() == -1, "listen socket still open"
        assert server._stop.is_set()


def _make_seeded_class():
    from mxnet_tpu.serve.replica import ReplicaServer

    class Seeded(ReplicaServer):
        def stop(self):
            # the PR-19 bug, verbatim shape: check-then-act on
            # http_server with no swap — two stoppers can both pass
            # the None check (double shutdown) or one can null the
            # attribute between the other's check and call
            # (AttributeError)
            self._stop.set()
            try:
                self.sock.close()
            except OSError:
                pass
            if self.http_server is not None:
                self.http_server.shutdown()
                self.http_server.server_close()
                self.http_server = None

    return Seeded


class SeededReplicaTeardown:
    name = "seeded-replica-teardown"
    budget = 96

    def run(self):
        from mxnet_tpu import sanitizer as _san

        server, http = _build_server(_make_seeded_class())
        state = {"server": server, "http": http}
        t1 = _san.thread(target=server.stop, name="stop1")
        t2 = _san.thread(target=server.stop, name="stop2")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        return state

    def check(self, state):
        http = state["http"]
        assert http.shutdowns == 1, \
            "http shutdown called %d times" % http.shutdowns
        assert http.closes == 1, \
            "http server_close called %d times" % http.closes
