"""Router-side scenarios: breaker half-open trial + probe/drain races.

Phase one races two ``allow()`` callers and a ``record_failure()``
against a breaker sitting at its half-open boundary (injected clock,
no wall time): at most ONE trial may be granted before anyone reports
back.  Phase two races a probe update, a drain toggle and an eject
toggle on a ReplicaHandle while ``eligible()`` reads the combined
state.  Invariants:

* half-open grants <= 1 across the racing allow() calls
* ``eligible()`` observed mid-drain is False
* after all toggles restore the good state, the handle is eligible
"""

from __future__ import annotations


class _Clock:
    """Deterministic injectable monotonic clock (set by the root
    between phases; never mutated while threads race)."""

    def __init__(self):
        self.value = 0.0

    def __call__(self):
        return self.value


class RouterScenario:
    name = "router"
    budget = 96

    def run(self):
        from mxnet_tpu import sanitizer as _san
        from mxnet_tpu.serve.router import CircuitBreaker, \
            ReplicaHandle

        state = {"grants": {}, "mid_drain": None, "final": None}

        # -- phase 1: half-open single-trial admission
        clk = _Clock()
        br = CircuitBreaker(failures=2, cooldown=10.0, clock=clk,
                            label="sched-breaker")
        br.record_failure()
        br.record_failure()          # open at t=0
        clk.value = 50.0             # past cooldown: half_open
        state["pre_state"] = br.state

        def trial(key):
            state["grants"][key] = br.allow()

        t1 = _san.thread(target=trial, args=("a",), name="trial-a")
        t2 = _san.thread(target=trial, args=("b",), name="trial-b")
        t3 = _san.thread(target=br.record_failure, name="failer")
        for t in (t1, t2, t3):
            t.start()
        for t in (t1, t2, t3):
            t.join()
        state["post_state"] = br.state

        # -- phase 2: probe / drain / eject vs eligible()
        h = ReplicaHandle("127.0.0.1", 1, key="sched-handle")

        def prober():
            h.note_probe({"live": True, "draining": False,
                          "models": {"m": {"ready": True}}})

        def drainer():
            h.set_draining(True)
            state["mid_drain"] = h.eligible("m")
            h.set_draining(False)

        def ejector():
            h.note_ejected(True)
            h.note_ejected(False)

        threads = [_san.thread(target=prober, name="prober"),
                   _san.thread(target=drainer, name="drainer"),
                   _san.thread(target=ejector, name="ejector")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        state["final"] = h.eligible("m")
        state["handle"] = h
        return state

    def check(self, state):
        assert state["pre_state"] == "half_open", state["pre_state"]
        grants = sum(1 for g in state["grants"].values() if g)
        # a failure report between the allow() calls may shrink the
        # window to zero grants, but two trials in flight at once is
        # the breaker bug this scenario exists to catch
        assert grants <= 1, state["grants"]
        # the racing record_failure always leaves it open (a
        # half-open failure re-opens; a third consecutive failure
        # keeps it open) and re-stamps the cooldown at t=50
        assert state["post_state"] == "open", state["post_state"]
        assert state["mid_drain"] is False, state["mid_drain"]
        assert state["final"] is True, state["final"]
        h = state["handle"]
        assert h._model_ready == {"m": True}, h._model_ready
        assert not h._draining and not h._ejected, \
            (h._draining, h._ejected)
