"""First-class checkable scenario units for the graftsched explorer.

A scenario is a class with:

* ``name``        — registry key / trace file stem
* ``budget``      — per-scenario schedule budget (optional)
* ``max_preemptions`` — context bound (optional, default 2)
* ``run(self)``   — executed inside the controlled root thread; builds
  the subsystem under test, drives it from a few spawned threads, and
  returns a *state* object.  Every sync primitive must come from the
  ``mxnet_tpu.sanitizer`` factories (they do, by construction) and the
  scenario must fake any real-I/O boundary (sockets, XLA dispatch):
  a controlled thread blocked in real I/O never reaches a yield point.
* ``check(self, state)`` — runs *uncontrolled* after each clean
  schedule; raises (usually AssertionError) to turn an invariant
  violation into a finding.

Scenarios must be deterministic modulo the schedule: no wall-clock
branches (pass ``max_wait_ms=0`` and generous timeouts so logical
timeouts, not real ones, drive control flow) and no unseeded
randomness on any path that reaches a yield point.
"""

from __future__ import annotations

from .batcher import BatcherScenario
from .checkpoint import CheckpointScenario
from .decode import DecodeScenario
from .kvserver import KVServerScenario
from .replica import ReplicaScenario, SeededReplicaTeardown
from .router import RouterScenario

# shipped drill set: every scenario here must explore its bounded
# schedule set with zero findings
SCENARIOS = {
    cls.name: cls
    for cls in (BatcherScenario, DecodeScenario, ReplicaScenario,
                RouterScenario, CheckpointScenario, KVServerScenario)
}

# the teeth check: a deliberately re-introduced historical bug
# (PR-19 ReplicaServer stop() double-teardown) that the explorer MUST
# find within budget — not part of the zero-findings drill set
SEEDED = {SeededReplicaTeardown.name: SeededReplicaTeardown}


def get(name):
    try:
        return SCENARIOS.get(name) or SEEDED[name]
    except KeyError:
        raise KeyError("unknown graftsched scenario %r (have: %s)"
                       % (name, ", ".join(sorted(SCENARIOS) +
                                          sorted(SEEDED))))


def names():
    return sorted(SCENARIOS)
