"""CheckpointManager scenario: concurrent background saves + rotation.

Two threads issue background saves for different epochs, the root
waits, then commits a third foreground save that triggers rotation
(``keep_last=2``).  Every schedule uses a fresh prefix (the
write+commit lock is cached per manifest path across manager
instances).  Invariants:

* ``wait()`` returns only after BOTH background commits are on disk
  (the lost-writer filter-then-reassign bug this scenario found)
* no background error leaked
* after the rotating save, exactly ``keep_last`` entries remain and
  the newest epoch is among them
"""

from __future__ import annotations

import os
import shutil
import tempfile

import numpy as _np


class CheckpointScenario:
    name = "checkpoint"
    budget = 48

    def run(self):
        from mxnet_tpu import ndarray as nd
        from mxnet_tpu import sanitizer as _san
        from mxnet_tpu.resilience.checkpoint import CheckpointManager

        tmp = tempfile.mkdtemp(prefix="graftsched-ckpt-")
        prefix = os.path.join(tmp, "model")
        mgr = CheckpointManager(prefix, keep_last=2, background=True)
        params = {"w": nd.array(_np.arange(2, dtype=_np.float32))}
        state = {"tmp": tmp, "mgr": mgr}

        def save(epoch):
            mgr.save_checkpoint(epoch, arg_params=params)

        t1 = _san.thread(target=save, args=(1,), name="save-1")
        t2 = _san.thread(target=save, args=(2,), name="save-2")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        mgr.wait()
        # wait() is the commit barrier: both epochs must be on disk
        # NOW, before rotation — record what it guaranteed
        state["after_wait"] = sorted(mgr.epochs())
        mgr.save_checkpoint(3, arg_params=params, background=False)
        state["after_rotate"] = mgr.epochs()
        return state

    def check(self, state):
        mgr = state["mgr"]
        try:
            assert state["after_wait"] == [1, 2], state["after_wait"]
            assert mgr._bg_error is None, mgr._bg_error
            assert mgr._pending == [], mgr._pending
            rotated = state["after_rotate"]
            assert len(rotated) == 2, rotated
            assert 3 in rotated, rotated
            assert rotated[-1] == 3, rotated
            assert set(rotated) - {3} <= {1, 2}, rotated
        finally:
            shutil.rmtree(state["tmp"], ignore_errors=True)
