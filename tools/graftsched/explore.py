"""graftsched explorer: iterative preemption bounding + DPOR-lite.

Runs a scenario under ``tools.graftsched.core.Scheduler`` repeatedly,
branching the recorded decision sequence at conflicting yield points:

* Bound 0 is the default (continue-current, lowest-tid) schedule.
* A *branch* forces a different enabled thread at one decision step
  (``overrides[step] = tid``); bound k schedules carry k overrides.
  BFS over the override sets realizes iterative context bounding —
  every 0-preemption schedule before any 1-preemption one, etc.
* DPOR-lite pruning: a branch (step i -> thread t') is generated only
  when the op granted at step i *conflicts* with some op t' performs
  later in the parent run (same object key, not both reads).
  Independent ops commute, so forcing the swap would reach an
  already-seen state.

A finding (deadlock, livelock, exception, invariant violation, replay
divergence) stops the scenario's exploration and serializes the
decision trace to JSON; ``replay()`` re-executes it bit-exactly.
"""

from __future__ import annotations

import json
import os

from . import core

try:
    from . import SCHEDULES_TOTAL, FINDINGS_TOTAL
except ImportError:  # pragma: no cover - circular-import guard
    SCHEDULES_TOTAL = FINDINGS_TOTAL = None

try:
    from mxnet_tpu.observability import events as _events
except Exception:  # pragma: no cover - standalone use
    _events = None

DEFAULT_BUDGET = int(os.environ.get("MXNET_SCHED_BUDGET", "128"))
DEFAULT_PREEMPTIONS = int(os.environ.get("MXNET_SCHED_PREEMPTIONS", "2"))

TRACE_VERSION = 1


def run_schedule(factory, overrides=None, replay=None, max_steps=None):
    """One schedule: fresh scenario instance, fresh scheduler.  Returns
    the scheduler's result dict (decisions/enabled_others/ops_by_tid/
    finding); the scenario's ``check(state)`` runs uncontrolled after a
    clean run and its failure becomes an ``invariant`` finding."""
    scn = factory()
    sch = core.Scheduler(overrides=overrides, replay=replay,
                         max_steps=max_steps
                         or getattr(scn, "max_steps", None))
    core.install(sch)
    box = {}

    def _root():
        box["state"] = scn.run()

    try:
        sch.run(_root)
    finally:
        core.uninstall()
    res = sch.result()
    if SCHEDULES_TOTAL is not None:
        SCHEDULES_TOTAL.inc()
    if res["finding"] is None:
        try:
            scn.check(box.get("state"))
        except BaseException as exc:  # noqa: BLE001 — becomes the finding
            import traceback
            res["finding"] = {
                "type": "invariant",
                "message": "%s: %s" % (type(exc).__name__, exc),
                "step": len(res["decisions"]),
                "stacks": [{"tid": -1, "name": "check",
                            "stack": traceback.format_exc().splitlines()}],
            }
    return res


def _conflicts(kind, key, t2_ops, after_step):
    """Does thread t2 perform an op after *after_step* that conflicts
    with (kind, key)?  key None (pure scheduling ops) never conflicts;
    two reads of the same attr are independent."""
    if key is None:
        return False
    for step, k2, key2 in t2_ops:
        if step <= after_step:
            continue
        if key2 == key and not (kind == "rd" and k2 == "rd"):
            return True
    return False


def explore(factory, name=None, budget=None, max_preemptions=None,
            max_steps=None, trace_dir=None):
    """Explore a scenario's bounded schedule space.  Returns a dict:
    ``{scenario, schedules, finding, trace_path, preemption_bound}``.
    Stops at the first finding and serializes its trace."""
    name = name or getattr(factory, "name", factory.__name__)
    budget = budget or getattr(factory, "budget", DEFAULT_BUDGET)
    if max_preemptions is None:
        max_preemptions = getattr(factory, "max_preemptions",
                                  DEFAULT_PREEMPTIONS)
    schedules = 0
    seen_overrides = set()
    seen_decisions = set()
    frontier = []                       # BFS: (overrides, result)
    finding = None
    finding_overrides = None
    finding_result = None

    root = run_schedule(factory, overrides={}, max_steps=max_steps)
    schedules += 1
    seen_overrides.add(frozenset())
    seen_decisions.add(tuple(map(tuple, root["decisions"])))
    if root["finding"] is not None:
        finding, finding_overrides, finding_result = \
            root["finding"], {}, root
    else:
        frontier.append(({}, root))

    i = 0
    while i < len(frontier) and finding is None and schedules < budget:
        overrides, parent = frontier[i]
        i += 1
        if len(overrides) >= max_preemptions:
            continue
        base = max(overrides) if overrides else -1
        decisions = parent["decisions"]
        enabled_others = parent["enabled_others"]
        ops_by_tid = parent["ops_by_tid"]
        for step in range(base + 1, len(decisions)):
            if finding is not None or schedules >= budget:
                break
            _tid, kind, key, _reason = decisions[step]
            for t2 in enabled_others[step]:
                if finding is not None or schedules >= budget:
                    break
                if not _conflicts(kind, key, ops_by_tid.get(t2, ()),
                                  step):
                    continue
                child_over = dict(overrides)
                child_over[step] = t2
                fs = frozenset(child_over.items())
                if fs in seen_overrides:
                    continue
                seen_overrides.add(fs)
                child = run_schedule(factory, overrides=child_over,
                                     max_steps=max_steps)
                schedules += 1
                if child["finding"] is not None:
                    finding, finding_overrides, finding_result = \
                        child["finding"], child_over, child
                    break
                dh = tuple(map(tuple, child["decisions"]))
                if dh not in seen_decisions:
                    seen_decisions.add(dh)
                    frontier.append((child_over, child))

    trace_path = None
    if finding is not None:
        if FINDINGS_TOTAL is not None:
            FINDINGS_TOTAL.inc()
        trace_path = write_trace(
            trace_dir or os.environ.get("MXNET_SCHED_TRACE_DIR", "/tmp"),
            name, finding_overrides, finding_result)
    if _events is not None:
        _events.emit("sched", kind="explore", scenario=name,
                     schedules=schedules,
                     findings=0 if finding is None else 1,
                     finding_type=None if finding is None
                     else finding["type"],
                     trace=trace_path)
    return {
        "scenario": name,
        "schedules": schedules,
        "finding": finding,
        "trace_path": trace_path,
        "preemption_bound": max_preemptions,
        "budget": budget,
    }


def write_trace(trace_dir, name, overrides, result):
    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, "graftsched-%s.trace.json" % name)
    payload = {
        "version": TRACE_VERSION,
        "scenario": name,
        "overrides": {str(k): v for k, v in (overrides or {}).items()},
        "decisions": [list(d) for d in result["decisions"]],
        "finding": result["finding"],
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    return path


def load_trace(path):
    with open(path) as fh:
        payload = json.load(fh)
    if payload.get("version") != TRACE_VERSION:
        raise ValueError("unsupported trace version %r in %s"
                         % (payload.get("version"), path))
    return payload


def replay(factory, trace, max_steps=None):
    """Re-execute a recorded trace bit-deterministically.  *trace* is a
    path or a loaded payload.  Returns the new run's result dict; the
    caller compares its finding/decisions against the recording."""
    if isinstance(trace, str):
        trace = load_trace(trace)
    decisions = [tuple(d) for d in trace["decisions"]]
    res = run_schedule(factory, replay=decisions, max_steps=max_steps)
    if _events is not None:
        _events.emit("sched", kind="replay", scenario=trace["scenario"],
                     steps=len(decisions),
                     finding_type=None if res["finding"] is None
                     else res["finding"]["type"])
    return res
