#!/usr/bin/env python
"""Transformer-LM training throughput (tokens/s + MFU) on one chip —
the long-context counterpart of bench.py's ResNet number (SURVEY §5.7;
the reference has no transformer to compare against, so the roofline
probe is the yardstick).

Flash attention (Pallas, causal block skipping) is on the hot path via
`gluon.contrib.nn.MultiHeadAttention`; the whole step is one donated
XLA program scanned scan_n deep (bench.timed_train_steps discipline).

    PYTHONPATH=/root/repo:/root/.axon_site python tools/benchmark_lm.py \
        [--dim 1024 --heads 16 --layers 12 --seq 2048 --batch 8]

Run only with a healthy tunnel and NO other TPU process.  On CPU
(JAX_PLATFORMS=cpu) shrinks shapes for a plumbing smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _lstm_lm(vocab, dim, layers):
    from mxnet_tpu.gluon.model_zoo.lm import get_lstm_lm
    return get_lstm_lm(vocab, dim, layers)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="transformer",
                    choices=["transformer", "lstm"])
    ap.add_argument("--dim", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--scan", type=int, default=5)
    ap.add_argument("--remat", default="none",
                    choices=["none", "dots", "full"],
                    help="activation rematerialization policy (long "
                         "sequences need 'dots' to fit HBM)")
    args = ap.parse_args()

    import mxnet_tpu as mx  # re-pins jax_platforms from the env var
    import jax
    import bench
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.transformer import get_transformer_lm
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if not on_tpu:
        args.dim, args.heads, args.layers = 64, 4, 2
        args.seq, args.batch, args.vocab = 128, 2, 64
        args.iters, args.scan = 4, 2
    if args.arch == "lstm":
        # reference LSTM-LM shapes: 2x650 medium / 2x1500 large PTB
        n_layers = max(2, args.layers // 6)
        net = _lstm_lm(args.vocab, args.dim, n_layers)
    else:
        n_layers = args.layers
        net = get_transformer_lm(vocab=args.vocab, dim=args.dim,
                                 heads=args.heads, layers=args.layers,
                                 max_seq=max(args.seq, 16))
    net.initialize()
    trainer = ParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
        mesh=make_mesh({"dp": 1}, [dev]),
        multi_precision=on_tpu,
        remat=None if args.remat == "none" else args.remat)

    rng = np.random.RandomState(0)
    # token ids travel as int32: a float32 id cast to bf16 by the
    # multi-precision input path rounds to multiples of 128 above 256,
    # silently corrupting every embedding lookup (integer dtypes are
    # exempt from the compute-dtype cast)
    x = mx.nd.array(rng.randint(0, args.vocab, (args.batch, args.seq))
                    .astype(np.int32), dtype="int32")
    y = mx.nd.array(rng.randint(0, args.vocab, (args.batch, args.seq))
                    .astype(np.float32))

    r = bench.timed_train_steps(trainer, x, y, args.iters, args.scan,
                                warmup=2)
    tokens = args.batch * args.seq
    tok_s = tokens * r["iters"] / r["dt"]
    flops = r["flops_per_step"]
    if not flops:
        # 6*P per token (fwd+bwd); transformer adds the attention
        # 12*S*D-per-token term, lstm has 8*D^2 params per layer
        if args.arch == "lstm":
            p_count = (args.vocab * args.dim * 2
                       + n_layers * 8 * args.dim * args.dim)
            flops = tokens * 6.0 * p_count
        else:
            p_count = (args.vocab * args.dim * 2
                       + n_layers * 12 * args.dim * args.dim)
            flops = tokens * (6.0 * p_count
                              + 12.0 * n_layers * args.seq * args.dim)
    out = {
        "metric": "%s_lm_train" % args.arch,
        "tokens_per_s": round(tok_s, 1),
        "ms_per_step": round(r["dt"] / r["iters"] * 1e3, 2),
        "batch": args.batch, "seq": args.seq, "dim": args.dim,
        "heads": args.heads, "layers": n_layers,
        "flops_per_step": flops,
        "final_loss": r["final_loss"],
        "device": getattr(dev, "device_kind", str(dev)),
    }
    if on_tpu:
        peak = bench._probe_peak_flops()
        out["mfu"] = round(flops * r["iters"] / r["dt"] / peak, 4)
        out["probe_tf_s"] = round(peak / 1e12, 1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
