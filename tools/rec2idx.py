#!/usr/bin/env python
"""Rebuild the .idx companion for a RecordIO file (reference:
tools/rec2idx.py — lost-index recovery so shuffled/indexed readers can
reopen an existing .rec).

    python tools/rec2idx.py data.rec data.idx
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record_file")
    ap.add_argument("index_file", nargs="?")
    args = ap.parse_args(argv)
    idx_path = args.index_file or \
        os.path.splitext(args.record_file)[0] + ".idx"

    from mxnet_tpu.recordio import MXRecordIO
    rec = MXRecordIO(args.record_file, "r")
    n = 0
    with open(idx_path, "w") as out:
        while True:
            pos = rec.tell()
            if rec.read() is None:
                break
            out.write("%d\t%d\n" % (n, pos))
            n += 1
    rec.close()
    print("wrote %d entries to %s" % (n, idx_path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
