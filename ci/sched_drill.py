"""CI drill: graftsched schedule exploration over the shipped scenarios.

Three gates, all bounded so the stage stays well under a minute:

1. **Shipped scenarios are finding-free** — every scenario in
   ``tools.graftsched.scenarios.SCENARIOS`` explores its bounded
   schedule set (iterative preemption bounding + DPOR pruning) with
   zero findings.  A finding prints the serialized trace path so the
   failure replays locally with ``python -m tools.graftsched
   --replay <trace>``.
2. **Teeth** — the seeded re-introduction of the PR-19 ReplicaServer
   stop() double-teardown MUST be found within its budget, and its
   trace MUST replay to the identical decision sequence and the same
   finding.  A checker that cannot re-find a bug it already found
   once is decoration.
3. **Counters moved** — ``graftsched_schedules_total`` grew by the
   schedules this drill ran and ``graftsched_findings_total`` by
   exactly the seeded finding.

Last stdout line is the scrapeable summary::

    graftsched: scenarios=N schedules=M findings=0 ok
"""

import logging
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
san = os.environ.get("MXNET_SAN", "")
if "sched" not in san and san != "all":
    os.environ["MXNET_SAN"] = (san + ",sched").lstrip(",")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

logging.disable(logging.WARNING)   # the decode rebuild path logs

import tools.graftsched as graftsched              # noqa: E402
from tools.graftsched import explore, scenarios    # noqa: E402

failures = []
trace_dir = tempfile.mkdtemp(prefix="graftsched-ci-")
t0 = time.monotonic()
total_schedules = 0

sched0 = graftsched.SCHEDULES_TOTAL.value
find0 = graftsched.FINDINGS_TOTAL.value

# -- gate 1: shipped scenarios explore clean ----------------------------
for name in scenarios.names():
    cls = scenarios.get(name)
    res = explore.explore(cls, trace_dir=trace_dir)
    total_schedules += res["schedules"]
    finding = res["finding"]
    if finding is None:
        print("  %-12s schedules=%-4d ok" % (name, res["schedules"]))
    else:
        failures.append(
            "scenario %r: %s finding after %d schedules — replay "
            "with: python -m tools.graftsched --replay %s\n%s"
            % (name, finding["type"], res["schedules"],
               res["trace_path"], finding["message"]))
        print("  %-12s schedules=%-4d FINDING=%s trace=%s"
              % (name, res["schedules"], finding["type"],
                 res["trace_path"]))

# -- gate 2: the seeded bug must be found and must replay ---------------
seeded_cls = scenarios.SEEDED["seeded-replica-teardown"]
res = explore.explore(seeded_cls, trace_dir=trace_dir)
total_schedules += res["schedules"]
finding = res["finding"]
if finding is None:
    failures.append(
        "teeth: the seeded ReplicaServer double-teardown was NOT "
        "found within %d schedules — the explorer lost its teeth"
        % res["schedules"])
else:
    print("  %-12s schedules=%-4d seeded finding=%s (expected)"
          % ("teeth", res["schedules"], finding["type"]))
    trace = explore.load_trace(res["trace_path"])
    rep = explore.replay(seeded_cls, trace)
    rf = rep["finding"]
    if list(rep["decisions"]) != [tuple(d) for d in trace["decisions"]]:
        failures.append("teeth replay diverged from the recorded "
                        "decision sequence (trace %s)"
                        % res["trace_path"])
    elif rf is None or rf["type"] != finding["type"] \
            or rf["message"] != finding["message"]:
        failures.append(
            "teeth replay did not reproduce the recorded finding "
            "(got %r, recorded %r; trace %s)"
            % (rf and rf["type"], finding["type"], res["trace_path"]))
    else:
        print("  %-12s replay bit-exact: same decisions, same finding"
              % "teeth")

# -- gate 3: the observability counters moved ---------------------------
sched_delta = graftsched.SCHEDULES_TOTAL.value - sched0
find_delta = graftsched.FINDINGS_TOTAL.value - find0
if sched_delta < total_schedules:
    failures.append("graftsched_schedules_total grew by %d, expected "
                    ">= %d" % (sched_delta, total_schedules))
if find_delta < 1:
    failures.append("graftsched_findings_total did not count the "
                    "seeded finding (delta %d)" % find_delta)

elapsed = time.monotonic() - t0
if elapsed > 60.0:
    failures.append("drill took %.1fs (budget 60s) — trim scenario "
                    "budgets" % elapsed)

if failures:
    print("\ngraftsched drill FAILED:")
    for f in failures:
        print("  - %s" % f)
    print("graftsched: scenarios=%d schedules=%d findings=%d FAIL"
          % (len(scenarios.names()), total_schedules, len(failures)))
    sys.exit(1)

print("graftsched: scenarios=%d schedules=%d findings=0 ok"
      % (len(scenarios.names()), total_schedules))
