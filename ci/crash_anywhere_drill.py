#!/usr/bin/env python
"""Crash-anywhere drill: a supervised training job killed at ARBITRARY
steps (plus one hang) must auto-recover and finish **bit-identical** to
an uninterrupted run, with zero replayed or skipped batches.

Proof structure (docs/resilience.md "Job-level fault tolerance"):

1. **Baseline** — one unsupervised child trains N steps end-to-end and
   records (a) sha256 of its final params + optimizer state, (b) the
   final metric value, (c) a per-batch sequence log (step -> batch
   content hash).
2. **Supervised** — the same child runs under
   ``resilience.supervisor`` with per-batch resumable checkpoints
   (``checkpoint_every_n_batches=1`` + ``resume_from='latest'``).
   Each incarnation is armed with a different seeded fault:

   * attempts 0..K-1: ``chaos.kill_at_step=<seeded step>`` —
     ``os._exit(137)`` at the start of that global step;
   * attempt K: ``chaos.hang_at_step=<seeded step>`` — the loop
     wedges, the heartbeat stalls, and the WATCHDOG must detect it
     (dead vs hung), dump a flight record, and kill;
   * final attempt: no faults — runs to completion.

3. **Assertions** — supervisor reports exactly the expected deaths +
   one hang; final params/opt-state/metric sha-identical to baseline;
   the merged sequence log (later incarnations own the trajectory
   from their resume point) covers steps 0..N-1 exactly once with the
   baseline's batch hashes — no replay, no skip; the hang produced a
   flight record with thread stacks and an events tail; events.jsonl
   is well-formed with a monotone seq across every restart.

Scrapeable last stdout line:
    crash_anywhere: kills=K hangs=1 steps=N bitexact=yes ok
"""

import json
import os
import random
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
PY = sys.executable

EPOCHS = 2
BATCHES = 6                      # per epoch
STEPS = EPOCHS * BATCHES
N_KILLS = 3
SEED = 20260803

CHILD = r'''
import hashlib, json, os, sys
sys.path.insert(0, os.environ["CA_REPO"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.resilience import CheckpointManager

workdir = os.environ["CA_DIR"]
epochs = int(os.environ["CA_EPOCHS"])
batches = int(os.environ["CA_BATCHES"])
batch_size = 16

def mlp():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Dropout(net, p=0.5, name="drop")   # proves RNG resume
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")

rng = np.random.RandomState(0)
X = rng.randn(batches * batch_size, 8).astype(np.float32)
Y = rng.randint(0, 4, batches * batch_size).astype(np.float32)
train = NDArrayIter(X, Y, batch_size=batch_size)

mx.random.seed(7)            # the framework's functional PRNG stream
mod = mx.Module(mlp(), context=mx.cpu())
mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_last=3)

seq_fd = os.open(os.path.join(workdir, "seqlog.jsonl"),
                 os.O_WRONLY | os.O_CREAT | os.O_APPEND)
attempt = int(os.environ.get("MXNET_SUPERVISOR_ATTEMPT", "-1"))

def log_batch(param):
    step = param.epoch * batches + param.nbatch
    batch = param.locals["data_batch"]
    h = hashlib.sha256(
        np.ascontiguousarray(batch.data[0].asnumpy()).tobytes()
    ).hexdigest()[:16]
    line = json.dumps({"run": attempt, "step": step, "h": h}) + "\n"
    os.write(seq_fd, line.encode())

mod.fit(train, num_epoch=epochs, optimizer="sgd", eval_metric="acc",
        optimizer_params={"learning_rate": 0.1},
        checkpoint_manager=mgr, checkpoint_every_n_batches=1,
        resume_from="latest", batch_end_callback=log_batch)

# ran to completion: fingerprint the full trained state
args, auxs = mod.get_params()
h = hashlib.sha256()
for name in sorted(args):
    h.update(np.ascontiguousarray(args[name].asnumpy()).tobytes())
for name in sorted(auxs):
    h.update(np.ascontiguousarray(auxs[name].asnumpy()).tobytes())
opt_h = hashlib.sha256(mod._optimizer_states_bytes() or b"").hexdigest()
final = {"params_sha": h.hexdigest(), "opt_sha": opt_h,
         "steps": mod._step_seq, "acc": None}
# the epoch's metric is reported through the job state machinery; for
# the drill fingerprint, rescore on the training set (deterministic)
m = mx.metric.create("acc")
train.reset()
mod.score(train, m)
final["acc"] = m.get()[1]
with open(os.path.join(workdir, "final.json"), "w") as f:
    json.dump(final, f)
'''


def run_child(workdir, extra_env=None):
    env = dict(os.environ)
    env.update({"CA_REPO": REPO, "CA_DIR": workdir,
                "CA_EPOCHS": str(EPOCHS), "CA_BATCHES": str(BATCHES)})
    env.update(extra_env or {})
    return subprocess.run([PY, "-c", CHILD], env=env, cwd=workdir,
                          capture_output=True, timeout=300)


def merged_trajectory(seqlog_path):
    """Replay the sequence log with resume semantics: when a new
    incarnation appears, it owns the trajectory from its first step
    onward (earlier incarnations' entries at >= that step were never
    committed — checkpoints are per-batch, so there are none to drop
    in the kill-at-step-start case, but the merge is general)."""
    final = {}
    last_run = None
    with open(seqlog_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec["run"] != last_run:
                last_run = rec["run"]
                for step in [s for s in final if s >= rec["step"]]:
                    del final[step]
            final[rec["step"]] = rec["h"]
    return final


def main():
    t0 = time.time()
    rng = random.Random(SEED)
    # seeded, arbitrary, distinct fault steps (never step 0: the first
    # checkpoint must exist for a resume to be exercised... actually
    # resume-from-nothing is also legal, but kills mid-run are the
    # interesting case) — ascending so every armed fault actually fires
    fault_steps = sorted(rng.sample(range(1, STEPS - 1), N_KILLS + 1))
    kill_steps, hang_step = fault_steps[:N_KILLS], fault_steps[-1]
    print("== crash_anywhere: %d steps, kills at %s, hang at %d =="
          % (STEPS, kill_steps, hang_step), flush=True)

    # -- 1. uninterrupted baseline ---------------------------------------
    base_dir = tempfile.mkdtemp(prefix="ca_base_")
    res = run_child(base_dir)
    assert res.returncode == 0, \
        "baseline child failed:\n%s" % res.stderr.decode()[-3000:]
    with open(os.path.join(base_dir, "final.json")) as f:
        baseline = json.load(f)
    base_traj = merged_trajectory(os.path.join(base_dir, "seqlog.jsonl"))
    assert sorted(base_traj) == list(range(STEPS)), \
        "baseline trajectory incomplete: %s" % sorted(base_traj)
    print("  baseline: params=%s acc=%.4f" % (baseline["params_sha"][:12],
                                              baseline["acc"]), flush=True)

    # -- 2. supervised run with seeded faults ----------------------------
    sup_dir = tempfile.mkdtemp(prefix="ca_sup_")
    os.environ["MXNET_OBS"] = "all"
    os.environ["MXNET_OBS_PATH"] = os.path.join(sup_dir, "events.jsonl")

    def env_for_attempt(attempt):
        env = {"CA_REPO": REPO, "CA_DIR": sup_dir,
               "CA_EPOCHS": str(EPOCHS), "CA_BATCHES": str(BATCHES),
               "MXNET_OBS": "all",
               "MXNET_OBS_PATH": os.environ["MXNET_OBS_PATH"]}
        if attempt < len(kill_steps):
            env["MXNET_CHAOS"] = "kill_at_step=%d" % kill_steps[attempt]
        elif attempt == len(kill_steps):
            env["MXNET_CHAOS"] = "hang_at_step=%d" % hang_step
        else:
            env["MXNET_CHAOS"] = ""
        return env

    from mxnet_tpu.resilience.supervisor import Supervisor
    sup = Supervisor([PY, "-c", CHILD], workdir=sup_dir,
                     timeout=4.0, max_restarts=N_KILLS + 2,
                     env_for_attempt=env_for_attempt)
    result = sup.run()
    assert result.ok, "supervised job never finished: %r" % result
    assert result.deaths == N_KILLS, \
        "expected %d kill-deaths, saw %d" % (N_KILLS, result.deaths)
    assert result.hangs == 1, \
        "expected exactly one hang, saw %d" % result.hangs
    print("  supervised: %d attempts, %d deaths, %d hang"
          % (result.attempts, result.deaths, result.hangs), flush=True)

    # -- 3a. bit-identical final state -----------------------------------
    with open(os.path.join(sup_dir, "final.json")) as f:
        sup_final = json.load(f)
    assert sup_final["params_sha"] == baseline["params_sha"], \
        "final params DIVERGED: %s vs %s" % (sup_final["params_sha"],
                                             baseline["params_sha"])
    assert sup_final["opt_sha"] == baseline["opt_sha"], \
        "final optimizer state diverged"
    assert sup_final["acc"] == baseline["acc"], \
        "final metric diverged: %r vs %r" % (sup_final["acc"],
                                             baseline["acc"])

    # -- 3b. no batch replayed or skipped --------------------------------
    traj = merged_trajectory(os.path.join(sup_dir, "seqlog.jsonl"))
    missing = [s for s in range(STEPS) if s not in traj]
    extra = [s for s in traj if not 0 <= s < STEPS]
    assert not missing and not extra, \
        "trajectory holes=%s extras=%s" % (missing, extra)
    wrong = [s for s in range(STEPS) if traj[s] != base_traj[s]]
    assert not wrong, \
        "replayed/reordered batches at steps %s" % wrong

    # -- 3c. flight record for the hang ----------------------------------
    assert len(result.flight_records) == 1, result.flight_records
    with open(result.flight_records[0]) as f:
        flight = json.load(f)
    assert flight["reason"] == "hang"
    assert flight["stacks_path"] and \
        os.path.getsize(flight["stacks_path"]) > 0, \
        "flight record has no thread stacks"
    assert flight["events_tail"], "flight record has no events tail"
    print("  flight record: %s (stacks %d bytes)"
          % (os.path.basename(result.flight_records[0]),
             os.path.getsize(flight["stacks_path"])), flush=True)

    # -- 3d. events.jsonl monotone seq across restarts -------------------
    seqs, cats = [], set()
    with open(os.environ["MXNET_OBS_PATH"]) as f:
        for line in f:
            rec = json.loads(line)      # raises on a torn line
            seqs.append(rec["seq"])
            cats.add(rec["ev"])
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs), \
        "events.jsonl seq not strictly monotone across restarts"
    assert {"supervisor", "watchdog"} <= cats, \
        "missing supervisor/watchdog events: %s" % sorted(cats)

    print("crash_anywhere: kills=%d hangs=1 steps=%d bitexact=yes ok"
          % (N_KILLS, STEPS), flush=True)
    print("  (%.1fs)" % (time.time() - t0), file=sys.stderr)


if __name__ == "__main__":
    main()
