"""Sanitizer-enabled CI smoke train step (ci/run_tests.sh stage).

Runs a short real training loop — fused train step + PrefetchingIter
data path + a local kvstore multi-device trainer — with ALL FOUR
graftsan components on (the stage exports MXNET_SAN=all), then fails
on:

* any sanitizer report (race/lockset, lock-order, recompile,
  donation, transfer),
* a broken one-program-per-step contract (fused_step dispatches must
  equal the step count; compiles must stay at warmup's one), on both
  the full-fused and the partial-fused (tree_apply) paths.

The point is drift protection: a new lock added without discipline, a
per-step recompile, or a hot-path host sync shows up HERE, in seconds,
with stacks — not as a flaky multi-process drill three PRs later.
"""

import os
import sys

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# two virtual CPU devices: the partial-fused (multi-device tree
# update) path only engages with >1 executor
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=2").strip()

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd, sym  # noqa: E402
from mxnet_tpu import profiler  # noqa: E402
from mxnet_tpu.io import NDArrayIter, PrefetchingIter  # noqa: E402
import tools.graftsan as graftsan  # noqa: E402

STEPS = 12


def build_module(contexts=None, kvstore=None):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, label, name="softmax")
    mod = mx.mod.Module(net, context=contexts or mx.cpu())
    mod.bind([("data", (16, 8))], [("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore=kvstore, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    failures = []

    # threaded data path: PrefetchingIter's producer thread runs under
    # the instrumented queue/event/thread wrappers
    it = PrefetchingIter(NDArrayIter(x, y, batch_size=16,
                                     last_batch_handle="discard"))

    # -- phase 1: full-fused path (single device, no kvstore) ---------
    mod = build_module()
    profiler.reset_counters()
    steps = 0
    while steps < STEPS:
        for batch in it:
            mod.forward_backward_update(batch)
            steps += 1
            if steps >= STEPS:
                break
        it.reset()
    dispatches = profiler.counter_value("fused_step_dispatches")
    compiles = profiler.counter_value("fused_step_compiles")
    if dispatches != STEPS:
        failures.append(
            "one-program-per-step broken: %d fused dispatches for %d "
            "steps (legacy fallback engaged?)" % (dispatches, STEPS))
    if compiles != 1:
        failures.append(
            "one-program-per-step broken: %d fused compiles (want "
            "exactly 1 warmup compile for %d steps)"
            % (compiles, STEPS))

    # -- phase 2: local kvstore push/pull + partial-fused path --------
    kv = mx.kv.create("local")
    kv.init("smoke", nd.ones((4,)))
    kv.push("smoke", nd.ones((4,)) * 2)
    out = nd.zeros((4,))
    kv.pull("smoke", out=out)
    assert out.asnumpy().tolist() == [2.0] * 4

    profiler.reset_counters()
    # multi-device, locally-reduced grads -> the jitted tree_apply
    # partial fusion (a local kvstore with update_on_kvstore would put
    # the updater store-side and fall back to the legacy loop)
    mod2 = build_module(contexts=[mx.cpu(0), mx.cpu(1)])
    it.reset()
    p_steps = 0
    for batch in it:
        mod2.forward_backward_update(batch)
        p_steps += 1
    tree_dispatches = profiler.counter_value("tree_apply_dispatches")
    tree_compiles = profiler.counter_value("tree_apply_compiles")
    if tree_dispatches != p_steps:
        failures.append(
            "partial-fused path broken: %d tree_apply dispatches for "
            "%d steps" % (tree_dispatches, p_steps))
    if tree_compiles != 1:
        failures.append(
            "partial-fused path recompiles: %d tree_apply compiles "
            "(want 1)" % tree_compiles)

    reports = graftsan.reports()
    for r in reports:
        failures.append(graftsan.format_report(r))

    # -- phase 3: donation drill ---------------------------------------
    # The CPU backend never donates, so without forcing the declared
    # donation this component would be INERT in CPU CI — force it and
    # prove a stale alias of a donated buffer raises at the touch
    # site.  Runs last: the deliberate trip adds a report.
    import warnings
    from mxnet_tpu.ops import registry as _registry
    from tools.graftsan.donation import UseAfterDonateError
    real_supports = _registry.supports_donation
    _registry.supports_donation = lambda: True
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # cpu ignores donation
            mod3 = build_module()
            it.reset()
            batch = next(iter(it))
            mod3.forward_backward_update(batch)
            ex3 = mod3._exec_group.execs[0]
            stale = mx.nd.NDArray(ex3.arg_dict["fc1_weight"]._data)
            mod3.forward_backward_update(batch)
        try:
            stale.asnumpy()
            failures.append("donation sanitizer inert: stale alias of "
                            "a donated buffer was readable")
        except UseAfterDonateError:
            pass
        if ex3.arg_dict["fc1_weight"].asnumpy().shape != (32, 8):
            failures.append("donation poison hit a LIVE rebound handle")
    finally:
        _registry.supports_donation = real_supports
    deliberate = [r for r in graftsan.reports()[len(reports):]]
    if [r for r in deliberate if r.component != "donation"]:
        failures.extend(graftsan.format_report(r) for r in deliberate
                        if r.component != "donation")

    print("graftsan smoke: full_steps=%d dispatches=%d compiles=%d | "
          "partial_steps=%d tree_dispatches=%d tree_compiles=%d | "
          "donation drill tripped | reports=%d"
          % (steps, dispatches, compiles, p_steps, tree_dispatches,
             tree_compiles, len(reports)))
    if failures:
        print("\n".join(failures), file=sys.stderr)
        print("graftsan smoke: FAIL", file=sys.stderr)
        return 1
    print("graftsan smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
