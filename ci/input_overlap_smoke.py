"""Input-pipeline overlap CI smoke (ci/run_tests.sh stage).

Proves host↔device input overlap ON CPU with the sanitizers armed:

* a host-bound synthetic iterator (X ms decode) feeding REAL fused
  train steps (Y ms device step, nonfinite guard armed so the serial
  path pays its per-step readback) runs serially at ≈ X+Y per step;
* the same job through a ``DevicePrefetcher`` ring + async guard
  readback (``MXNET_GUARD_READBACK_LAG``) reaches a steady state of
  ≈ max(X, Y) — asserted as pipelined < 0.7× serial;
* the run must produce ZERO graftsan reports (the stage exports
  ``MXNET_SAN=all``, so the ring's queue/locks/producer thread and the
  async readback run fully instrumented);
* the observability contract holds: ``input_wait_seconds`` observed
  once per consumed batch, ``steps_input_stalled_total`` and
  ``device_prefetch_ring_occupancy`` registered,
  ``device_put_elided_total`` counting the step loop's skipped puts.

One measurement retry is allowed: the drill times real sleeps against
real compute on shared-CPU CI, and a scheduler hiccup during the
~5-second window must not fail the build on its own (a genuine overlap
regression fails BOTH attempts).  Last stdout line is the scrapeable
summary (``inputperf: stall_share=.. ok``), mirroring the other
stages.  See docs/perf_input_pipeline.md.
"""

import os
import sys

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import bench  # noqa: E402  (import has no side effects)
import tools.graftsan as graftsan  # noqa: E402
from mxnet_tpu.observability import metrics  # noqa: E402


def main():
    failures = []

    out = bench.compare_input_paths()
    if not out["overlap_ok"]:
        print("input overlap below the bar (pipelined %.2f ms/step vs "
              "serial %.2f); retrying once for CI scheduler noise"
              % (out["pipelined_ms_per_step"],
                 out["serial_ms_per_step"]), file=sys.stderr)
        out = bench.compare_input_paths()
    if not out["overlap_ok"]:
        failures.append(
            "pipelined input path did not overlap: %.2f ms/step vs "
            "serial %.2f ms/step (want < 0.7x; decode %.2f ms, step "
            "%.2f ms)" % (out["pipelined_ms_per_step"],
                          out["serial_ms_per_step"], out["decode_ms"],
                          out["step_ms"]))

    # -- sanitizers saw the whole run and stayed silent ----------------
    reports = graftsan.reports()
    for r in reports:
        failures.append(graftsan.format_report(r))

    # -- observability contract ----------------------------------------
    snap = metrics.snapshot()
    checks = {
        "input_wait_seconds": lambda s: s["count"] >= 16,
        "steps_input_stalled_total": lambda s: s["value"] >= 0,
        "device_prefetch_ring_occupancy": lambda s: True,
        "device_put_elided_total": lambda s: s["value"] >= 16,
    }
    for name, check in checks.items():
        if name not in snap:
            failures.append("instrument %r missing from the registry"
                            % name)
        elif not check(snap[name]):
            failures.append("instrument %r has unexpected value: %r"
                            % (name, snap[name]))

    if failures:
        for f in failures:
            print("input overlap smoke FAILURE: %s" % f,
                  file=sys.stderr)
    print("inputperf: serial=%.1f pipelined=%.1f steps/s "
          "speedup=%.2fx stall_share=%.3f reports=%d %s"
          % (out["serial_steps_per_s"], out["pipelined_steps_per_s"],
             out["speedup"], out["input_stall_share"], len(reports),
             "FAIL" if failures else "ok"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
