"""Autotuner CI smoke (ci/run_tests.sh stage).

A REAL measured search, end to end, in seconds: a tiny FC model, a
short synthetic serve trace shaped so the space's default coalescing
window demonstrably costs latency (low rate, small requests — every
request pays the full window before dispatch), ~8 candidates through
the successive-halving loop with the analytic prior pruning, winner
persisted to a TuningStore, and the store picked up by a fresh
``ModelRegistry.load`` with MXNET_SAN=all auditing every lock/thread
the measurement replays spin up.  Gates:

* the search completes and measures the default at full budget;
* the winner is never worse than the default on the same trace (the
  baseline guard — tuning must not be able to regress);
* every paid measurement was feasible (zero request-path compiles);
* the store round-trips: reload from disk gives the same entry, with
  the trace identity (sha256) and the measurement artifact attached;
* a fresh registry + ``MXNET_TUNING_STORE`` applies the winning
  ladder/knobs (health(name) reports the tuning) and serves the SAME
  trace with zero request-path compiles;
* identical trace + identical seed => identical winner (the search
  is deterministic given its measurements — asserted on the stub-free
  schedule by re-running the proposal phase);
* zero graftsan reports from the autotuner's replays.

Last stdout line is the scrapeable summary::

    autotune: trials=N pruned=M winner_gain=X% ok
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "autotune")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="autotune_smoke_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import tools.graftsan as graftsan  # noqa: E402
from mxnet_tpu.autotune import (TuningStore, serve_space,  # noqa: E402
                                synth_serve_trace, tune)
from mxnet_tpu.autotune.measure import ServeMeasurer, fc_model  # noqa: E402
from mxnet_tpu.autotune.search import serve_objective  # noqa: E402
from mxnet_tpu.observability import events  # noqa: E402

DIM = 16
MODEL = "autotune-smoke"


def main():
    failures = []
    tmp = tempfile.mkdtemp(prefix="autotune_store_")
    store_path = os.path.join(tmp, "tuning.json")

    # low rate + small requests: the default 2 ms coalescing window is
    # pure added latency (nothing arrives to coalesce with), so a
    # tuned window near zero wins on merit, not noise
    trace = synth_serve_trace(rate=60.0, seconds=1.0, dim=DIM,
                              rows_lo=1, rows_hi=2, seed=9)
    space = serve_space()
    measurer = ServeMeasurer(trace, name=MODEL)
    store = TuningStore.load(store_path, missing_ok=True)
    try:
        result = tune(space, measurer, serve_objective(),
                      model=MODEL, workload="serve", trials=8,
                      neighbor_trials=2, seed=0, short_frac=0.3,
                      store=store, device="cpu")
    finally:
        measurer.close()

    # -- search gates --------------------------------------------------
    if result["score"] is None:
        failures.append("winner has no finite score: %r"
                        % (result["measurement"],))
    if result["baseline_score"] is None:
        failures.append("default was not measured at full budget")
    elif result["score"] is not None and \
            result["score"] > result["baseline_score"]:
        failures.append(
            "baseline guard broken: winner %r worse than default %r"
            % (result["score"], result["baseline_score"]))
    if result["gain_pct"] < 0:
        failures.append("negative gain recorded: %r"
                        % (result["gain_pct"],))
    for part in ("measurement", "baseline"):
        m = result[part]
        if m.get("request_path_compiles"):
            failures.append("%s replay compiled in the request path: "
                            "%r" % (part, m))

    # -- store round-trip ----------------------------------------------
    reloaded = TuningStore.load(store_path)
    entry = reloaded.get(MODEL, "serve", device="cpu")
    if entry is None:
        failures.append("store round-trip lost the entry")
    else:
        if entry["config"] != json.loads(json.dumps(
                result["entry"]["config"])):
            failures.append("store round-trip changed the config: "
                            "%r vs %r" % (entry["config"],
                                          result["entry"]["config"]))
        if entry.get("trace", {}).get("sha256") != trace.sha256():
            failures.append("stored entry lost the trace identity")
        if not entry.get("measurement", {}).get("ok"):
            failures.append("stored entry lost the measurement "
                            "artifact: %r" % (entry.get("measurement"),))

    # -- registry pickup: serve the same trace off the tuned config ----
    os.environ["MXNET_TUNING_STORE"] = store_path
    from mxnet_tpu import serve
    from mxnet_tpu.autotune import trace as trace_mod
    net, params, data_shapes = fc_model(DIM)
    registry = serve.ModelRegistry()
    try:
        pred = registry.load(MODEL, net, params,
                             data_shapes=data_shapes)
        if (pred.tuning or {}).get("config") != entry["config"]:
            failures.append("registry did not attach the tuned entry: "
                            "%r" % (pred.tuning,))
        want_ladder = tuple(entry["config"].get("ladder") or ())
        if want_ladder and pred.ladder.batches != want_ladder:
            failures.append("registry ignored the tuned ladder: %r vs "
                            "%r" % (pred.ladder.batches, want_ladder))
        health = registry.health(MODEL)
        if health.get("tuning", {}).get("gain_pct") != \
                result["gain_pct"]:
            failures.append("health(name) does not surface the "
                            "tuning: %r" % (health.get("tuning"),))
        batcher = registry.batcher(MODEL)
        warm = pred.compile_count
        records, _wall = trace_mod.replay(
            trace, lambda x, _i: batcher.submit(x))
        for _slot, _t, fut in records:
            fut.result(60)
        if pred.compile_count != warm:
            failures.append(
                "tuned config compiled in the request path: %d new"
                % (pred.compile_count - warm))
    finally:
        registry.close()
        os.environ.pop("MXNET_TUNING_STORE", None)

    # -- events + sanitizers -------------------------------------------
    try:
        evs = events.read_events(events.path())
    except (OSError, ValueError):
        evs = []
    kinds = {e.get("kind") for e in evs if e.get("ev") == "autotune"}
    if not {"trial_start", "trial_result", "winner"} <= kinds:
        failures.append("autotune events incomplete: %s"
                        % sorted(kinds))

    reports = graftsan.reports()
    failures.extend(graftsan.format_report(r) for r in reports)

    if failures:
        print("\n".join(failures), file=sys.stderr)
        print("autotune smoke: FAIL", file=sys.stderr)
        print("autotune: trials=%d pruned=%d winner_gain=%s%% FAIL"
              % (result["trials"], result["pruned"],
                 result["gain_pct"]))
        return 1
    print("autotune: trials=%d pruned=%d winner_gain=%s%% ok"
          % (result["trials"], result["pruned"], result["gain_pct"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
