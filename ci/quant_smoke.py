"""Quantized-serving CI smoke (ci/run_tests.sh stage).

The int8 pipeline end to end under MXNET_SAN=all: calibrate a small
conv+FC model on synthetic batches, save/load the table through the
atomic round-trip, quantize, load into a ModelRegistry and serve
CONCURRENT mixed-size traffic through a real DynamicBatcher.  Gates:

* int8 dot/conv ops provably present in the lowered StableHLO of
  EVERY rung;
* load-time accuracy gate passed at every rung (and a deliberately
  strict policy fails typed — a quantized model can never serve
  silently-wrong answers);
* a corrupted calibration table fails the load typed at the sha
  check, never quantizes;
* zero request-path compiles under the mixed-size traffic;
* quantize events balanced: every lower has a matching gate /
  gate_failed, calibrate events carry the table sha;
* the new instruments move (serve_quantized_models gauge up then
  back down, quant_calibration_batches_total,
  quant_accuracy_gate_failures_total);
* zero graftsan reports.

Last stdout line is the scrapeable summary::

    quant: layers=N covered=M acc_ok compiles=0 ok
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "quantize,serve")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="quant_smoke_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import tools.graftsan as graftsan  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.observability import events  # noqa: E402
from mxnet_tpu.observability import metrics  # noqa: E402
from mxnet_tpu.quantize import (CalibTable, QuantizationError,  # noqa: E402
                                QuantizePolicy, calibrate,
                                hlo_has_int8_compute)
from mxnet_tpu.serve.buckets import BucketLadder  # noqa: E402
from mxnet_tpu.serve.registry import ModelRegistry  # noqa: E402

MODEL = "quant-smoke"
RUNGS = (1, 2, 4)
SHAPE = (3, 12, 12)


def build_model():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=4,
                            name="qc1")
    a1 = mx.sym.Activation(data=c1, act_type="relu", name="qa1")
    p1 = mx.sym.Pooling(data=a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", name="qp1")
    f1 = mx.sym.FullyConnected(data=p1, num_hidden=8, name="qf1")
    rs = np.random.RandomState(0)
    arg_shapes, _, _ = f1.infer_shape(data=(1,) + SHAPE)
    params = {n: nd.array(rs.randn(*s).astype(np.float32) * 0.15)
              for n, s in zip(f1.list_arguments(), arg_shapes)
              if n != "data"}
    return f1, params


def main():
    failures = []
    rs = np.random.RandomState(1)
    sym, params = build_model()
    batches = [rs.randn(4, *SHAPE).astype(np.float32)
               for _ in range(5)]

    # -- calibration + atomic table round-trip -------------------------
    table = calibrate(sym, params, batches, name=MODEL)
    tmp = tempfile.mkdtemp(prefix="quant_calib_")
    path = os.path.join(tmp, "calib.json")
    table.save(path)
    loaded = CalibTable.load(path)
    if loaded.sha != table.sha or loaded.ranges != table.ranges:
        failures.append("calib table atomic round-trip drifted: "
                        "%s vs %s" % (loaded.sha, table.sha))

    # a corrupted table must fail the LOAD typed, never quantize
    broken_path = os.path.join(tmp, "broken.json")
    doc = json.load(open(path))
    doc["calib_table"]["ranges"]["qc1"] = [-99.0, 99.0]
    open(broken_path, "w").write(json.dumps(doc))
    registry = ModelRegistry()
    report = {"total": 0, "covered": 0}
    compiles = -1
    try:
        try:
            registry.load(MODEL, sym, params,
                          data_shapes={"data": (4,) + SHAPE},
                          quantize="int8", calib=broken_path)
            failures.append("corrupted calib table quantized a model")
        except QuantizationError:
            pass

        # an impossible accuracy threshold must fail the gate typed
        try:
            registry.load(MODEL, sym, params,
                          data_shapes={"data": (4,) + SHAPE},
                          ladder=BucketLadder(batches=RUNGS),
                          quantize=QuantizePolicy(mode="int8",
                                                  max_rel_err=1e-12),
                          calib=path)
            failures.append("accuracy gate passed at 1e-12")
        except QuantizationError:
            pass

        # -- the real quantized load ----------------------------------
        pred = registry.load(MODEL, sym, params,
                             data_shapes={"data": (4,) + SHAPE},
                             ladder=BucketLadder(batches=RUNGS),
                             quantize="int8", calib=path)
        report = pred.quantization
        if report["calib_sha"] != table.sha:
            failures.append("served calib sha %r != table sha %r"
                            % (report["calib_sha"], table.sha))
        if report["covered"] != report["total"] or \
                report["covered"] < 2:
            failures.append("incomplete coverage: %r"
                            % (report["layers"],))
        for b in RUNGS:
            if not hlo_has_int8_compute(
                    pred.lowered_text(pred.rung_shapes(b))):
                failures.append("rung %d lost its int8 compute" % b)
            gate = report["gate"]["rungs"].get(b)
            if gate is None or gate["rel_err"] > 0.1:
                failures.append("rung %d accuracy gate: %r"
                                % (b, gate))
        health = registry.health(MODEL)
        if health.get("quantization", {}).get("mode") != "int8":
            failures.append("health(name) lost the quantization "
                            "section: %r" % (health,))

        # -- concurrent mixed-size traffic, zero request-path compiles -
        batcher = registry.batcher(MODEL)
        warm = pred.compile_count
        if pred.jit_cache_size() != 0:
            failures.append("jit cache not empty after warm")
        futs = [batcher.submit(
            rs.randn(1 + (i % 4), *SHAPE).astype(np.float32))
            for i in range(40)]
        for f in futs:
            f.result(60)
        compiles = pred.compile_count - warm
        if compiles:
            failures.append("request path compiled %d new programs"
                            % compiles)
        if pred.jit_cache_size() != 0:
            failures.append("request path leaked into the jit cache")

        # quantized outputs actually match fp32 on live traffic
        x = rs.randn(2, *SHAPE).astype(np.float32)
        ref = sym.bind(args={**params, "data": nd.array(x)}) \
            .forward()[0].asnumpy()
        out = np.asarray(batcher.submit(x).result(60)[0])
        err = float(np.abs(out - ref).max() / np.abs(ref).max())
        if err > 0.1:
            failures.append("served quantized output drifted: rel "
                            "err %.4f" % err)

        # -- instruments ----------------------------------------------
        snap = metrics.snapshot()
        if snap.get("serve_quantized_models", {}).get("value") != 1:
            failures.append("serve_quantized_models gauge != 1 while "
                            "loaded: %r"
                            % snap.get("serve_quantized_models"))
        if snap.get("quant_calibration_batches_total",
                    {}).get("value", 0) < len(batches):
            failures.append("quant_calibration_batches_total did not "
                            "count the calibration")
        if snap.get("quant_accuracy_gate_failures_total",
                    {}).get("value", 0) < 1:
            failures.append("quant_accuracy_gate_failures_total did "
                            "not count the strict-policy failure")
    finally:
        registry.close()
    snap = metrics.snapshot()
    if snap.get("serve_quantized_models", {}).get("value") != 0:
        failures.append("serve_quantized_models gauge != 0 after "
                        "close: %r" % snap.get("serve_quantized_models"))

    # -- balanced quantize events --------------------------------------
    try:
        evs = events.read_events(events.path())
    except (OSError, ValueError):
        evs = []
    qevs = [e for e in evs if e.get("ev") == "quantize"]
    kinds = {}
    for e in qevs:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    if not kinds.get("calibrate"):
        failures.append("no calibrate event emitted")
    if kinds.get("lower", 0) != \
            kinds.get("gate", 0) + kinds.get("gate_failed", 0):
        failures.append("unbalanced quantize events: %r" % (kinds,))
    for e in qevs:
        if e["kind"] == "calibrate" and \
                e.get("sha") != table.sha[:12]:
            failures.append("calibrate event lost the sha: %r" % (e,))

    reports = graftsan.reports()
    failures.extend(graftsan.format_report(r) for r in reports)

    line = "quant: layers=%d covered=%d acc_ok compiles=%d %s" % (
        report["total"], report["covered"], compiles,
        "ok" if not failures else "FAIL")
    if failures:
        print("\n".join(failures), file=sys.stderr)
        print("quant smoke: FAIL", file=sys.stderr)
        print(line)
        return 1
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
