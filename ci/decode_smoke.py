"""Continuous-batching decode CI drill (ci/run_tests.sh stage).

Sixteen decode sessions with staggered prompt lengths run through the
paged KV pool and the continuous-batching tick loop (MXNET_SAN=all —
the sanitizers audit every lock/thread in the path), joining and
leaving mid-stream, with one session cancelled mid-decode and a
deliberate pool-exhaustion + recovery phase.  Gates:

* **bit-equality** — every session's generated token stream equals
  its SOLO dense-cache decode (the same step function, one dense
  worst-case cache, one dispatch per token — the PR-9 DecodeSession
  discipline).  Block-table gather/scatter, co-tenant garbage, rung
  padding and join/leave churn must be invisible in the tokens;
* **one compile per rung** — tick programs = session rungs, prefill
  programs = sequence rungs, all built at warm; ZERO compiles in the
  request path;
* **typed shedding** — admission past the pool's capacity raises
  KVPoolExhausted; after sessions release their blocks the same
  admission succeeds (exhaust -> recover);
* **zero leaks** — every pool block is free and the active-session
  gauge is back to zero at the end;
* **quarantine-and-rebuild** — a chaos-armed tick crash quarantines
  the suspect pool, rebuilds a fresh one against the WARM programs
  (zero new compiles asserted), re-admits every journaled session via
  one re-prefill, and the finished streams are still bit-equal to the
  solo dense decode; past ``MXNET_SERVE_DECODE_REBUILDS`` the next
  crash degrades to a typed ServeError (unhealthy, never wedged);
* **zero graftsan reports**; decode events (session_start/session_end,
  tick, journal, rebuild, pool_exhausted) recorded and consistent.

The event-balance gate runs with ``MXNET_OBS_RATE=0`` (uncapped):
the default 200 events/sec cap silently drops session_start/
session_end under CPU contention, which was the root cause of the
historical "events unbalanced" flake — an accounting artifact of the
rate limiter, not a decode bug.

Last stdout line is the scrapeable summary::

    decode: sessions=N ticks=M compiles=K rebuilds=R ok
"""

import os
import sys
import tempfile
import time
import warnings

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "decode")
# Uncapped events: the 200/sec default drops start/end events under
# CPU contention and breaks the balance gate (the old flake).
os.environ.setdefault("MXNET_OBS_RATE", "0")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="decode_smoke_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.observability import events as obs_events  # noqa: E402
from mxnet_tpu.observability import metrics as obs_metrics  # noqa: E402
from mxnet_tpu.resilience import chaos  # noqa: E402
from mxnet_tpu.serve.buckets import (RequestCancelled,  # noqa: E402
                                     ServeError)
from mxnet_tpu.serve.decode import (DecodeBatcher,  # noqa: E402
                                    DecodeEngine)
from mxnet_tpu.serve.kvpool import KVPoolExhausted  # noqa: E402
from mxnet_tpu.test_utils import (dense_decode_reference,  # noqa: E402
                                  tiny_attention_lm)
import tools.graftsan as graftsan  # noqa: E402

VOCAB, DIM = 32, 16
BLOCK = 4
MAX_LEN = 48
SESSIONS = 16
LATE_JOINS = 4
RUNGS = (1, 2, 4, 8, 16)


def dense_reference(params, step_fn, prompt, n_new, padded_len):
    """Solo dense-cache decode — the shared oracle from test_utils
    (what a lone PR-9 DecodeSession computes: one dense worst-case
    cache, one dispatch per token)."""
    return dense_decode_reference(params, step_fn, prompt, n_new,
                                  padded_len, DIM)


def main():
    failures = []
    params, step_fn, prefill_fn, token_spec, input_spec = \
        tiny_attention_lm(vocab=VOCAB, dim=DIM, seed=17)

    rs = np.random.RandomState(29)
    prompts = [rs.randint(0, VOCAB, size=int(ln)).astype(np.int32)
               for ln in rs.randint(1, 17, size=SESSIONS + LATE_JOINS)]
    n_new = [int(n) for n in rs.randint(4, 21,
                                        size=SESSIONS + LATE_JOINS)]
    # pool sized for every session's full growth plus a little slack
    # (phase 3 exhausts it deliberately, phase 1 must never)
    blocks_full = sum(-(-(len(p) + n) // BLOCK)
                      for p, n in zip(prompts, n_new))

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")      # CPU XLA ignores donation
        engine = DecodeEngine(
            step_fn, prefill_fn, token_spec, input_spec, params=params,
            max_len=MAX_LEN, block_size=BLOCK,
            num_blocks=blocks_full + 4, session_rungs=RUNGS,
            donate=True, label="drill")
        warm_compiles = engine.compile_count
        expect_compiles = len(RUNGS) + len(engine.prefill_rungs)
        if warm_compiles != expect_compiles:
            failures.append(
                "warm built %d programs, expected %d (one per tick "
                "rung %s + one per prefill rung %s)"
                % (warm_compiles, expect_compiles, RUNGS,
                   engine.prefill_rungs))
        batcher = DecodeBatcher(engine, max_wait_ms=2.0)

        # -- phase 1: staggered join/leave + one mid-decode cancel ----
        sessions = []
        for i in range(SESSIONS):
            sessions.append(batcher.start({"tok": prompts[i]},
                                          max_new_tokens=n_new[i]))
            if i % 5 == 4:
                time.sleep(0.002)    # joins land between ticks
        # a session with an unbounded budget, cancelled mid-decode
        victim = batcher.start({"tok": prompts[0][:2]},
                               max_new_tokens=10 ** 6)
        while victim.token_count < 3 and not victim.done():
            time.sleep(0.001)
        victim.cancel()
        # late joins while the first wave is mid-stream
        for i in range(SESSIONS, SESSIONS + LATE_JOINS):
            sessions.append(batcher.start({"tok": prompts[i]},
                                          max_new_tokens=n_new[i]))
        streams = []
        for s in sessions:
            try:
                streams.append([int(o) for o in s.result(60)])
            except Exception as exc:
                failures.append("session %d failed: %r" % (s.sid, exc))
                streams.append(None)
        try:
            victim.result(60)
            failures.append("cancelled session resolved cleanly")
        except RequestCancelled:
            pass
        except Exception as exc:
            failures.append("cancel resolved wrong: %r" % (exc,))
        victim_tokens = [int(o) for o in victim.outputs()]
        if len(victim_tokens) < 3:
            failures.append("cancel lost accepted steps: %d delivered"
                            % len(victim_tokens))

        # bit-equality: every stream vs its solo dense-cache decode
        mismatches = 0
        for i, (s, stream) in enumerate(zip(sessions, streams)):
            if stream is None:
                continue
            ref = dense_reference(params, step_fn, prompts[i],
                                  n_new[i], engine.padded_len)
            if stream != ref:
                mismatches += 1
                if mismatches <= 3:
                    failures.append(
                        "session %d stream != solo dense decode "
                        "(prompt len %d): %s vs %s"
                        % (s.sid, len(prompts[i]), stream, ref))
        ref_v = dense_reference(params, step_fn, prompts[0][:2],
                                len(victim_tokens), engine.padded_len)
        if victim_tokens != ref_v:
            failures.append("cancelled session's delivered prefix is "
                            "not bit-equal to its dense decode")

        # -- phase 2: drain the batcher, keep the engine --------------
        if not batcher.drain(30.0):
            failures.append("drain timed out with finished sessions")
        batcher.close()

        # -- phase 3: exhaust then recover the pool (direct mode) -----
        fillers = []
        exhausted = False
        for _ in range(engine.pool.blocks_total + 2):
            try:
                fillers.append(engine.admit(
                    {"tok": prompts[0][:4]}, max_new_tokens=1))
            except KVPoolExhausted:
                exhausted = True
                break
        if not exhausted:
            failures.append("pool never exhausted after %d admissions"
                            % len(fillers))
        for f in fillers:
            engine.release(f, "finished", None)
        try:
            recovered = engine.admit({"tok": prompts[1]},
                                     max_new_tokens=n_new[1])
            engine.prefill(recovered)
            while not recovered.done():
                engine.tick([recovered])
            rec_stream = [int(o) for o in recovered.result(10)]
            ref = dense_reference(params, step_fn, prompts[1],
                                  n_new[1], engine.padded_len)
            if rec_stream != ref:
                failures.append("post-recovery stream is not "
                                "bit-equal to its dense decode")
        except KVPoolExhausted:
            failures.append("pool did not recover after release")

        # -- gates ----------------------------------------------------
        if engine.compile_count != warm_compiles:
            failures.append(
                "%d compiles happened in the REQUEST PATH"
                % (engine.compile_count - warm_compiles))
        if engine.pool.blocks_in_use != 0:
            failures.append("leaked %d pool blocks"
                            % engine.pool.blocks_in_use)
        snap = obs_metrics.snapshot()
        gauge = snap.get("serve_decode_active_sessions", {})
        if gauge.get("value") != 0:
            failures.append("active-session gauge did not return to "
                            "zero: %r" % (gauge,))
        ticks = engine.dispatch_count
        total_compiles = engine.compile_count
        engine.close()

        # -- phase 4: tick-crash quarantine-and-rebuild ---------------
        # A chaos-armed crash in the coalesced tick loop: the batcher
        # must quarantine the suspect pool, rebuild a fresh one
        # against the WARM programs (zero new compiles), re-admit the
        # journaled sessions via one re-prefill each, and finish every
        # stream bit-equal to the solo dense decode.  Past the rebuild
        # budget the next crash degrades to a typed ServeError.
        eng2 = DecodeEngine(
            step_fn, prefill_fn, token_spec, input_spec, params=params,
            max_len=MAX_LEN, block_size=BLOCK, num_blocks=16,
            session_rungs=(1, 2), donate=True, label="rebuild")
        bat2 = DecodeBatcher(eng2, name="rebuild", max_wait_ms=2.0,
                             rebuilds=2)
        c0 = eng2.compile_count
        r_prompts = [list(prompts[0][:3]), list(prompts[1][:2])]
        r_new = 8
        r_refs = [dense_reference(params, step_fn, p, r_new,
                                  eng2.padded_len) for p in r_prompts]
        chaos.configure(decode_tick_raise_at=3)
        try:
            rsessions = [bat2.start({"tok": np.asarray(p, np.int32)},
                                    max_new_tokens=r_new)
                         for p in r_prompts]
            rstreams = []
            for s in rsessions:
                try:
                    rstreams.append([int(o) for o in s.result(60)])
                except Exception as exc:
                    failures.append("session %d lost across rebuild: "
                                    "%r" % (s.sid, exc))
                    rstreams.append(None)
        finally:
            chaos.reset()
        for st, ref in zip(rstreams, r_refs):
            if st is not None and st != ref:
                failures.append("post-rebuild stream != solo dense "
                                "decode: %s vs %s" % (st, ref))
        if eng2.compile_count != c0:
            failures.append(
                "rebuild compiled %d NEW programs (must rebuild "
                "against warm programs)" % (eng2.compile_count - c0))
        if bat2.rebuild_count != 1:
            failures.append("expected exactly 1 rebuild, got %d"
                            % bat2.rebuild_count)
        if bat2.health_state() != "ready":
            failures.append("batcher not ready after rebuild: %r"
                            % bat2.health_state())
        if eng2.pool.blocks_in_use != 0:
            failures.append("rebuild leaked %d pool blocks"
                            % eng2.pool.blocks_in_use)
        # burn the second (last) budgeted rebuild...
        chaos.configure(decode_tick_raise_at=1,
                        decode_tick_raise_for=1)
        try:
            s = bat2.start({"tok": np.asarray(r_prompts[0], np.int32)},
                           max_new_tokens=4)
            got = [int(o) for o in s.result(60)]
            if got != r_refs[0][:4]:
                failures.append("second-rebuild stream is not "
                                "bit-equal: %s vs %s"
                                % (got, r_refs[0][:4]))
        except Exception as exc:
            failures.append("second rebuild failed: %r" % (exc,))
        finally:
            chaos.reset()
        # ...then the crash PAST the budget must fail typed, not wedge
        chaos.configure(decode_tick_raise_at=1)
        try:
            s = bat2.start({"tok": np.asarray(r_prompts[0], np.int32)},
                           max_new_tokens=4)
            try:
                s.result(60)
                failures.append("past-budget crash resolved cleanly "
                                "instead of failing typed")
            except ServeError:
                pass
            except Exception as exc:
                failures.append("past-budget failure not typed: %r"
                                % (exc,))
        finally:
            chaos.reset()
        if not bat2.unhealthy:
            failures.append(
                "batcher not unhealthy past the rebuild budget")
        rebuilds = bat2.rebuild_count
        bat2.close()
        eng2.close()

    # decode events: starts == ends, tick + pool_exhausted present
    try:
        evs = [e for e in obs_events.read_events()
               if e.get("ev") == "decode"]
    except OSError:
        evs = []
    kinds = {}
    for e in evs:
        kinds[e.get("kind")] = kinds.get(e.get("kind"), 0) + 1
    if kinds.get("session_start", 0) != kinds.get("session_end", 0):
        failures.append("decode events unbalanced: %d starts vs %d "
                        "ends" % (kinds.get("session_start", 0),
                                  kinds.get("session_end", 0)))
    for kind in ("session_start", "session_end", "tick",
                 "pool_exhausted", "journal", "rebuild", "resume"):
        if not kinds.get(kind):
            failures.append("no %r decode event recorded (have %s)"
                            % (kind, sorted(kinds)))

    reports = graftsan.reports()
    failures.extend(graftsan.format_report(r) for r in reports)

    n_sessions = SESSIONS + LATE_JOINS
    if failures:
        print("\n".join(failures), file=sys.stderr)
        print("decode smoke: FAIL", file=sys.stderr)
        print("decode: sessions=%d ticks=%d compiles=%d rebuilds=%d "
              "FAIL" % (n_sessions, ticks, total_compiles, rebuilds))
        return 1
    print("decode: sessions=%d ticks=%d compiles=%d rebuilds=%d ok"
          % (n_sessions, ticks, total_compiles, rebuilds))
    return 0


if __name__ == "__main__":
    sys.exit(main())
