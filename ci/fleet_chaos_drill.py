#!/usr/bin/env python
"""Fleet-chaos drill: 3 real replica processes behind the router,
driven through the fleet fault classes under concurrent load
(ci/run_tests.sh stage, MXNET_SAN=all).

Scenarios (see mxnet_tpu/resilience/servechaos.py for the injection
points and docs/serving.md "Serving fleet"):

  baseline   concurrent load over 3 healthy replicas: every answer
             bit-equal to the eager forward at some rung, and the
             replicas' dispatch counters SUM to the answered request
             count with zero dedup hits — the exactly-once proof
  kill       one replica armed with replica_kill_at=K dies holding a
             request mid-load: the router fails the request over
             (same id), every accepted request still lands bit-equal
             or fails typed, and fleet.replace spawns a successor
             that warms from the shared persistent compile cache
             with ZERO new cache entries and ZERO request-path
             compiles under traffic
  decode-kill  (streaming decode) a replica armed with
             replica_kill_decode_at=K dies holding a DECODE rpc
             mid-stream under concurrent predict load: every open
             stream resumes transparently from the router journal on
             a survivor — the full token stream bit-equal to the
             SOLO dense-cache decode, zero request-path compiles on
             the survivors, zero leaked KV pool blocks, and the
             failover/resume counters advance
  deploy     fleet.deploy() cycles all 3 replicas onto checkpoint v2
             under concurrent load: zero dropped/failed requests,
             every answer bit-equal to v1 or v2, only v2 after the
             deploy completes, and the drain record reports zero
             abandoned work per replica
  decode-deploy  (streaming decode) the same deploy rolls under
             ACTIVE decode sessions: live sessions are evicted typed
             at drain (journal handoff), every stream resumes on a
             successor and finishes bit-equal to the solo dense
             decode, with zero request-path compiles after the warm
             start and zero leaked pool blocks
  partition  fleet_partition_at cuts router<->replica traffic to one
             replica: requests fail over, staleness ejects it from
             the rotation, healing the partition rejoins it, and the
             fleet serves through all of it with zero lost requests

Cross-cutting: every submitter thread joins (nothing hangs), every
submitted request/stream resolves (nothing is lost), the fleet scrape
aggregates 3 ready replicas, and the fleet+decode event trail records
failover/eject/rejoin/deploy and journal/session_place/failover/
resume/migrate.  Bounded child cleanup on any failure.

Scrapeable last stdout line::

    fleet: replicas=N faults=M recovered=K ok
"""

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "fleet,decode")
os.environ.setdefault("MXNET_OBS_RATE", "0")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="fleet_chaos_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import model as model_mod  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.observability import events as obs_events  # noqa: E402
from mxnet_tpu.observability import metrics as obs_metrics  # noqa: E402
from mxnet_tpu.resilience import chaos  # noqa: E402
from mxnet_tpu.serve import Fleet, ServeError  # noqa: E402
from mxnet_tpu.test_utils import (dense_decode_reference,  # noqa: E402
                                  tiny_attention_lm)

DIM = 8
BATCHES = (1, 2, 4)
REPLICAS = 3

# the streaming-decode workload: the deterministic tiny attention LM
# (same seed on every replica -> identical params -> bit-equal
# cross-replica failover); max_len sized so deploy-time streams are
# long-lived enough to be caught LIVE by the rolling drains
DVOCAB, DDIM, DSEED = 32, 16, 5
DMAX_LEN = 128
DECODE_SPEC = {"name": "lm", "kind": "decode_lm", "vocab": DVOCAB,
               "dim": DDIM, "seed": DSEED, "dtype": "float32",
               "max_len": DMAX_LEN, "block_size": 4,
               "num_blocks": 320, "rungs": [1, 2, 4]}

failures = []
faults = 0
recovered = 0


def check(ok, msg):
    if not ok:
        failures.append(msg)
    return ok


def build_checkpoints(tmp):
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="h")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="o")
    net = sym.softmax(net)
    prefix = os.path.join(tmp, "m")
    versions = {}
    for epoch, seed in ((1, 0), (2, 1)):
        rs = np.random.RandomState(seed)
        arg_shapes, _, _ = net.infer_shape(data=(1, DIM))
        params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.1)
                  for n, s in zip(net.list_arguments(), arg_shapes)
                  if n != "data"}
        model_mod.save_checkpoint(prefix, epoch, net, params, {})
        versions[epoch] = params
    return net, prefix, versions


def eager_refs(net, params, x):
    """x zero-padded through the eager forward at every rung it could
    land on (bit-equality anchor, the serve drill discipline)."""
    refs = []
    rows = x.shape[0]
    for b in BATCHES:
        if b < rows:
            continue
        padded = np.zeros((b, DIM), x.dtype)
        padded[:rows] = x
        args = dict(params)
        args["data"] = mx.nd.array(padded)
        refs.append(net.bind(mx.cpu(), args).forward()[0]
                    .asnumpy()[:rows])
    return refs


def drive(fleet, xs, refsets, threads=6, per_thread=12,
          allow_typed=False, tag=""):
    """Concurrent load through the router.  Returns answered count.
    Every submitted request must resolve: bit-equal to SOME ref set,
    or (when *allow_typed*) fail with a typed ServeError — never an
    untyped error, never a hang."""
    answered = [0]
    lock = threading.Lock()

    def worker(tid):
        for i in range(per_thread):
            idx = (tid * per_thread + i) % len(xs)
            try:
                out = fleet.router.predict("m", {"data": xs[idx]})
            except ServeError as exc:
                if not allow_typed:
                    with lock:
                        failures.append(
                            "%s: worker %d request %d failed typed "
                            "unexpectedly: %r" % (tag, tid, i, exc))
                continue
            except Exception as exc:    # noqa: BLE001 - the gate
                with lock:
                    failures.append(
                        "%s: worker %d request %d UNTYPED failure: %r"
                        % (tag, tid, i, exc))
                continue
            if not any(np.array_equal(out[0], r)
                       for refs in refsets for r in refs[idx]):
                with lock:
                    failures.append(
                        "%s: worker %d request %d not bit-equal to "
                        "eager at any rung/version" % (tag, tid, i))
            with lock:
                answered[0] += 1

    ts = [threading.Thread(target=worker, args=(t,), daemon=True)
          for t in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    hung = [t for t in ts if t.is_alive()]
    check(not hung, "%s: %d submitter thread(s) HUNG" % (tag, len(hung)))
    return answered[0], time.monotonic() - t0


def cache_entries(fleet):
    try:
        return len(os.listdir(fleet.compile_cache_dir))
    except OSError:
        return 0


def scenario_baseline(fleet, xs, refs_v1):
    global recovered
    n, dt = drive(fleet, xs, [refs_v1], tag="baseline")
    check(n == 6 * 12, "baseline: %d/72 answered" % n)
    # exactly-once: with no faults, the replicas' dispatch counters
    # sum to the answered count and nothing came from dedup
    dispatched = 0
    dups = 0
    for key in fleet.keys():
        stats = fleet.stats(key)
        dispatched += stats["predicts_dispatched"]
        dups += stats["dup_hits"]
    check(dispatched == n,
          "baseline: dispatched %d != answered %d (exactly-once)"
          % (dispatched, n))
    check(dups == 0, "baseline: %d unexpected dedup hits" % dups)
    view = fleet.scrape()
    check(view["ready"] == REPLICAS,
          "baseline: scrape sees %d/%d ready" % (view["ready"],
                                                 REPLICAS))
    for key, entry in view["replicas"].items():
        check(entry.get("scraped") and
              "mxnet_serve_requests_total" in entry.get("metrics", {}),
              "baseline: replica %s scrape incomplete" % key)
    if not failures:
        recovered += 1
    print("  baseline: %d answered in %.1fs, %d dispatched across %d "
          "replicas" % (n, dt, dispatched, REPLICAS))


def scenario_kill(fleet, xs, refs_v1):
    global faults, recovered
    before = len(failures)
    # replace one replica with one armed to die on its 5th predict
    victim = fleet.keys()[0]
    armed = fleet.replace(victim,
                          extra_env={"MXNET_CHAOS": "replica_kill_at=5"})
    fleet.wait_routable(count=REPLICAS)
    n, dt = drive(fleet, xs, [refs_v1], threads=6, per_thread=10,
                  tag="kill")
    check(n == 60, "kill: %d/60 answered (failover must cover the "
                   "killed replica)" % n)
    rec = fleet.record(armed)
    deadline = time.monotonic() + 30
    while rec["proc"].poll() is None and time.monotonic() < deadline:
        time.sleep(0.1)
    check(rec["proc"].poll() == 137,
          "kill: armed replica rc=%r, expected 137"
          % (rec["proc"].poll(),))
    faults += 1
    failed_over = obs_metrics.snapshot().get(
        "fleet_requests_failed_over_total", {}).get("value", 0)
    check(failed_over >= 1,
          "kill: no failover was recorded (counter=%s)" % failed_over)
    # successor warms from the shared compile cache: zero NEW entries
    entries_before = cache_entries(fleet)
    successor = fleet.replace(armed)
    fleet.wait_routable(count=REPLICAS)
    check(cache_entries(fleet) == entries_before,
          "kill: successor added %d compile-cache entries (expected "
          "0 — warm start)" % (cache_entries(fleet) - entries_before))
    # zero request-path compiles on the successor under traffic
    warm_compiles = dict(fleet.stats(successor)["compile_count"])
    n2, _ = drive(fleet, xs, [refs_v1], threads=4, per_thread=6,
                  tag="kill-post")
    check(n2 == 24, "kill: %d/24 post-replace answered" % n2)
    check(fleet.stats(successor)["compile_count"] == warm_compiles,
          "kill: successor compiled in the request path (%r -> %r)"
          % (warm_compiles, fleet.stats(successor)["compile_count"]))
    if len(failures) == before:
        recovered += 1
    print("  kill: %d+%d answered around a 137-kill, successor warm "
          "from cache in-rotation" % (n, n2))


def scenario_decode_kill(fleet, xs, refs_v1, dref):
    """Scenario E: a replica armed to die on its 6th DECODE rpc is
    killed mid-stream under concurrent predict load.  Every open
    stream must resume transparently from the router journal on a
    survivor — the full token stream bit-equal to the solo dense
    decode — with zero request-path compiles on the survivors and
    zero leaked KV pool blocks."""
    global faults, recovered
    before = len(failures)
    victim = fleet.keys()[0]
    armed = fleet.replace(victim, extra_env={
        "MXNET_CHAOS": "replica_kill_decode_at=6"})
    fleet.wait_routable(count=REPLICAS, model="m")
    fleet.wait_routable(count=REPLICAS, model="lm")
    survivors = [k for k in fleet.keys() if k != armed]
    warm = {k: fleet.stats(k)["decode"]["lm"]["compile_count"]
            for k in survivors}
    snap0 = obs_metrics.snapshot()
    fo0 = snap0.get("serve_decode_failovers_total",
                    {}).get("value", 0)
    rs0 = snap0.get("serve_decode_resumed_sessions_total",
                    {}).get("value", 0)
    prompt = np.array([3, 1, 2], dtype=np.int32)
    n_new = 12
    ref = dref(n_new)
    # round-robin placement spreads 6 streams over 3 replicas — at
    # least one lands on the armed replica, whose NEXT polls then
    # trip the kill mid-stream
    streams = [fleet.router.decode_open("lm", {"tok": prompt},
                                        max_new_tokens=n_new)
               for _ in range(2 * REPLICAS)]
    check(any(s.replica == armed for s in streams),
          "decode-kill: no stream placed on the armed replica")
    load = {}

    def _drive():
        n, dt = drive(fleet, xs, [refs_v1], threads=4, per_thread=8,
                      tag="decode-kill-load")
        load["n"] = n
    loader = threading.Thread(target=_drive, daemon=True)
    loader.start()
    for s in streams:
        try:
            got = [int(np.asarray(t)) for t in s.result(timeout=120)]
        except Exception as exc:    # noqa: BLE001 - the gate
            failures.append("decode-kill: stream %d LOST: %r"
                            % (s.seq, exc))
            continue
        if got != ref:
            failures.append(
                "decode-kill: stream %d not bit-equal to the solo "
                "dense decode: %s vs %s" % (s.seq, got, ref))
    loader.join(timeout=120)
    check(not loader.is_alive(), "decode-kill: predict load hung")
    check(load.get("n") == 32,
          "decode-kill: %r/32 predicts answered under the kill"
          % (load.get("n"),))
    rec = fleet.record(armed)
    deadline = time.monotonic() + 30
    while rec["proc"].poll() is None and time.monotonic() < deadline:
        time.sleep(0.1)
    check(rec["proc"].poll() == 137,
          "decode-kill: armed replica rc=%r, expected 137"
          % (rec["proc"].poll(),))
    faults += 1
    moved = [s for s in streams if s.failover_count >= 1]
    check(moved, "decode-kill: no stream failed over")
    check(all(s.replica != armed for s in moved),
          "decode-kill: a resumed stream still points at the corpse")
    snap = obs_metrics.snapshot()
    check(snap.get("serve_decode_failovers_total",
                   {}).get("value", 0) > fo0,
          "decode-kill: serve_decode_failovers_total did not advance")
    check(snap.get("serve_decode_resumed_sessions_total",
                   {}).get("value", 0) > rs0,
          "decode-kill: serve_decode_resumed_sessions_total did not "
          "advance")
    for k in survivors:
        check(fleet.stats(k)["decode"]["lm"]["compile_count"]
              == warm[k],
              "decode-kill: survivor %s compiled in the request path "
              "during failover" % k)
    fleet.replace(armed)
    fleet.wait_routable(count=REPLICAS, model="lm")
    for s in streams:
        s.close()
    view = fleet.scrape()
    for key, entry in view["replicas"].items():
        blocks = entry.get("metrics", {}).get(
            "mxnet_serve_kv_blocks_in_use")
        check(blocks == 0,
              "decode-kill: replica %s leaked %r KV pool blocks"
              % (key, blocks))
    resume_ms = [1e3 * (b - a) for s in moved
                 for a, b in s.resume_stamps]
    if len(failures) == before:
        recovered += 1
    print("  decode-kill: %d streams bit-equal around a 137-kill "
          "(%d resumed, %.1fms worst resume), %r predicts answered"
          % (len(streams), len(moved),
             max(resume_ms) if resume_ms else -1.0, load.get("n")))


def scenario_deploy(fleet, prefix, xs, refs_v1, refs_v2, dref):
    global recovered
    before = len(failures)
    spec_v2 = [{"name": "m", "prefix": prefix, "epoch": 2,
                "data_shapes": {"data": [1, DIM]},
                "batches": list(BATCHES)},
               dict(DECODE_SPEC)]
    # scenario F rides the same deploy: long-lived decode streams
    # opened BEFORE the roll, only partially delivered — every one of
    # their replicas will be cycled, so every stream must hand off
    # through its journal (drain eviction or dead-handle resume) and
    # still finish bit-equal on a successor
    d_new = 120
    dref_full = dref(d_new)
    dprompt = np.array([3, 1, 2], dtype=np.int32)
    dstreams = [fleet.router.decode_open("lm", {"tok": dprompt},
                                         max_new_tokens=d_new)
                for _ in range(REPLICAS + 1)]
    for s in dstreams:
        for _ in range(2):
            s.next_output(timeout=60)
    stop = threading.Event()
    load_failures = []
    answered = [0]
    lock = threading.Lock()

    def submitter(tid):
        n = 0
        while not stop.is_set():
            idx = (tid + n) % len(xs)
            n += 1
            try:
                out = fleet.router.predict("m", {"data": xs[idx]})
            except Exception as exc:    # noqa: BLE001 - the gate
                with lock:
                    load_failures.append("deploy: submitter %d: %r"
                                         % (tid, exc))
                return
            ok = any(np.array_equal(out[0], r)
                     for refs in (refs_v1, refs_v2)
                     for r in refs[idx])
            if not ok:
                with lock:
                    load_failures.append(
                        "deploy: submitter %d: request %d not "
                        "bit-equal to v1 or v2" % (tid, idx))
                return
            with lock:
                answered[0] += 1

    threads = [threading.Thread(target=submitter, args=(t,),
                                daemon=True) for t in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    entries_before = cache_entries(fleet)
    deploys_before = obs_metrics.snapshot().get(
        "fleet_deploys_total", {}).get("value", 0)
    t0 = time.monotonic()
    fleet.deploy(spec_v2)
    deploy_dt = time.monotonic() - t0
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=120)
    check(not any(t.is_alive() for t in threads),
          "deploy: submitter thread hung")
    failures.extend(load_failures)
    check(answered[0] > 40,
          "deploy: only %d requests answered under load" % answered[0])
    check(cache_entries(fleet) == entries_before,
          "deploy: successors added %d compile-cache entries "
          "(expected 0 — warm start)"
          % (cache_entries(fleet) - entries_before))
    check(obs_metrics.snapshot()["fleet_deploys_total"]["value"]
          == deploys_before + 1, "deploy: fleet_deploys_total did "
          "not advance")
    # post-deploy: ONLY v2 answers
    for x, refs in zip(xs[:4], (refs_v2[i] for i in range(4))):
        out = fleet.router.predict("m", {"data": x})
        check(any(np.array_equal(out[0], r) for r in refs),
              "deploy: post-deploy answer is not v2")
    if len(failures) == before:
        recovered += 1
    print("  deploy: rolled 3 replicas to v2 in %.1fs with %d live "
          "requests answered, 0 new cache entries"
          % (deploy_dt, answered[0]))

    # -- scenario F: the decode streams across that deploy ------------
    before_decode = len(failures)
    rs0 = obs_metrics.snapshot().get(
        "serve_decode_resumed_sessions_total", {}).get("value", 0)
    warm = {k: fleet.stats(k)["decode"]["lm"]["compile_count"]
            for k in fleet.keys()}
    for s in dstreams:
        try:
            got = [int(np.asarray(t)) for t in s.result(timeout=120)]
        except Exception as exc:    # noqa: BLE001 - the gate
            failures.append("deploy: decode stream %d LOST across "
                            "the roll: %r" % (s.seq, exc))
            continue
        if got != dref_full:
            failures.append(
                "deploy: decode stream %d not bit-equal across the "
                "roll (first diff at %s)"
                % (s.seq, next((i for i, (a, b)
                                in enumerate(zip(got, dref_full))
                                if a != b), "len")))
    # every original replica was cycled with the streams only 2/120
    # delivered — each stream MUST have migrated at least once
    check(all(s.failover_count >= 1 for s in dstreams),
          "deploy: a decode stream finished without migrating off "
          "its cycled replica")
    check(obs_metrics.snapshot().get(
              "serve_decode_resumed_sessions_total",
              {}).get("value", 0) > rs0,
          "deploy: no decode session resume was recorded")
    evs = obs_events.read_events(obs_events.path())
    evicted = sum(int(e.get("decode_evicted") or 0) for e in evs
                  if e.get("ev") == "fleet"
                  and e.get("kind") == "deploy_drain")
    check(evicted >= 1,
          "deploy: no LIVE decode session was evicted at drain "
          "(journal handoff never exercised)")
    for k in fleet.keys():
        check(fleet.stats(k)["decode"]["lm"]["compile_count"]
              == warm[k],
              "deploy: decode resume compiled in the request path "
              "on %s" % k)
    for s in dstreams:
        s.close()
    view = fleet.scrape()
    for key, entry in view["replicas"].items():
        blocks = entry.get("metrics", {}).get(
            "mxnet_serve_kv_blocks_in_use")
        check(blocks == 0,
              "deploy: replica %s leaked %r KV pool blocks after "
              "the migrated streams finished" % (key, blocks))
    if len(failures) == before_decode:
        recovered += 1
    print("  decode-deploy: %d streams migrated across the roll "
          "(%d evicted live at drain), all bit-equal"
          % (len(dstreams), evicted))


def scenario_partition(fleet, xs, refs_v2):
    global faults, recovered
    before = len(failures)
    victim_key = fleet.keys()[0]
    victim_port = fleet.record(victim_key)["port"]
    handle = fleet.router.handle(victim_key)
    chaos.configure(fleet_partition_at=1, fleet_partition_for=1000000,
                    fleet_partition_port=victim_port)
    try:
        n, _ = drive(fleet, xs, [refs_v2], threads=4, per_thread=8,
                     tag="partition")
        check(n == 32, "partition: %d/32 answered during the cut" % n)
        # staleness ejects the cut replica from the rotation
        deadline = time.monotonic() + 20
        while not handle.ejected and time.monotonic() < deadline:
            time.sleep(0.1)
        check(handle.ejected,
              "partition: replica was never ejected on staleness")
    finally:
        fired = chaos.fired("fleet_partition_at")
        chaos.reset()       # heal the partition
    check(fired >= 1, "partition: injection never fired")
    faults += fired
    # probes flow again: the replica rejoins
    deadline = time.monotonic() + 20
    while handle.ejected and time.monotonic() < deadline:
        time.sleep(0.1)
    check(not handle.ejected,
          "partition: replica did not rejoin after healing")
    n2, _ = drive(fleet, xs, [refs_v2], threads=4, per_thread=6,
                  tag="partition-post")
    check(n2 == 24, "partition: %d/24 answered after rejoin" % n2)
    # the rejoined replica serves again
    post = fleet.stats(victim_key)["predicts_dispatched"]
    check(post >= 1, "partition: rejoined replica served nothing")
    if len(failures) == before:
        recovered += 1
    print("  partition: %d+%d answered across cut/eject/rejoin "
          "(%d sends cut)" % (n, n2, fired))


def check_event_trail():
    evs = obs_events.read_events(obs_events.path())
    kinds = {e.get("kind") for e in evs if e.get("ev") == "fleet"}
    for expected in ("spawn", "reap", "failover", "eject", "rejoin",
                     "deploy", "deploy_drain", "replica_drain",
                     "decode_open"):
        check(expected in kinds,
              "event trail: no fleet %r event (have %s)"
              % (expected, sorted(kinds)))
    dkinds = {e.get("kind") for e in evs if e.get("ev") == "decode"}
    for expected in ("journal", "session_start", "session_end",
                     "session_place", "failover", "resume",
                     "migrate"):
        check(expected in dkinds,
              "event trail: no decode %r event (have %s)"
              % (expected, sorted(dkinds)))
    drains = [e for e in evs if e.get("ev") == "fleet"
              and e.get("kind") == "deploy_drain"]
    check(all(e.get("timed_out") is False and
              e.get("waited_requests") is not None for e in drains),
          "event trail: deploy_drain events lack the zero-abandoned "
          "drain record")


def main():
    global recovered
    tmp = tempfile.mkdtemp(prefix="fleet_drill_")
    net, prefix, versions = build_checkpoints(tmp)
    rs = np.random.RandomState(42)
    xs = [rs.randn(rs.randint(1, 4), DIM).astype(np.float32)
          for _ in range(12)]
    refs_v1 = {i: eager_refs(net, versions[1], x)
               for i, x in enumerate(xs)}
    refs_v2 = {i: eager_refs(net, versions[2], x)
               for i, x in enumerate(xs)}

    spec_v1 = [{"name": "m", "prefix": prefix, "epoch": 1,
                "data_shapes": {"data": [1, DIM]},
                "batches": list(BATCHES)},
               dict(DECODE_SPEC)]
    # solo dense-cache decode oracle for the streaming scenarios
    # (same lm seed as every replica's spec entry)
    dparams, dstep, _, _, _ = tiny_attention_lm(
        vocab=DVOCAB, dim=DDIM, seed=DSEED)
    dref_cache = {}

    def dref(n):
        if n not in dref_cache:
            dref_cache[n] = dense_decode_reference(
                dparams, dstep, [3, 1, 2], n, DMAX_LEN, DDIM)
        return dref_cache[n]

    t0 = time.monotonic()
    fleet = Fleet(spec_v1, replicas=REPLICAS, workdir=tmp,
                  max_wait_ms=1.0,
                  router_kwargs={"probe_interval": 0.2,
                                 "eject_timeout": 1.0,
                                 "retries": 4})
    try:
        fleet.start()
        print("  fleet: %d replicas up in %.1fs (%d cache entries)"
              % (REPLICAS, time.monotonic() - t0,
                 cache_entries(fleet)))
        scenario_baseline(fleet, xs, refs_v1)
        scenario_kill(fleet, xs, refs_v1)
        scenario_decode_kill(fleet, xs, refs_v1, dref)
        scenario_deploy(fleet, prefix, xs, refs_v1, refs_v2, dref)
        scenario_partition(fleet, xs, refs_v2)
        check_event_trail()
    finally:
        chaos.reset()
        fleet.stop()

    if failures:
        for f in failures:
            print("fleet drill FAILURE: %s" % f, file=sys.stderr)
    print("fleet: replicas=%d faults=%d recovered=%d/6 %s"
          % (REPLICAS, faults, recovered,
             "FAIL" if failures else "ok"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
