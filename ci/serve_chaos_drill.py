#!/usr/bin/env python
"""Serve-chaos drill: the serving request path driven through every
injected fault class (ci/run_tests.sh stage).

The injections live at the PRODUCTION choke points (see
mxnet_tpu/resilience/servechaos.py and docs/serving.md "Serving fault
tolerance"): the batcher's dispatcher consults ``on_dispatch`` before
every coalesced batch, the predictor consults ``on_warm`` before
every AOT program build.  Scenarios:

  overload    slow dispatches (armed through the MXNET_CHAOS env
              spec, the production wire format) back the queue up
              against a small request cap: submits past it shed with
              a typed OverloadError, every ACCEPTED request still
              completes bit-equal — overload never OOMs and never
              strands a caller
  expiry      the dispatcher is wedged (dispatch_hang_at) while a
              deadlined request waits: the request expires with a
              typed DeadlineExceededError and its payload provably
              NEVER reaches a dispatch; the un-deadlined request
              queued behind it completes
  crash       dispatch_raise_at escapes the dispatcher loop:
              supervision fails exactly the failing batch's futures,
              restarts the thread (jittered backoff), and the next
              batch serves normally
  unhealthy   crashes past the restart budget: the batcher goes
              unhealthy, submits shed typed, readiness and liveness
              probes flip false, and teardown still works
  liveness    a wedged dispatch with work queued goes stale on the
              health surface (Registry.live() false), recovers when
              released, and both requests land correct
  drain       unload(drain=True) under concurrent submit load with
              slow dispatches: every accepted request completes
              bit-equal to the eager forward at some rung, later
              submits shed typed, nothing hangs
  warm        reject_warm_at fails a load mid-warm: the model never
              half-registers (no name, no health entry), and the
              retried load serves

Cross-cutting asserts: ZERO stranded futures (every future any
scenario accepted resolves with a result or a typed error), and the
health state machine walked its full cycle in events.jsonl
(loading -> warming -> ready -> draining, plus ready -> unhealthy).

Deterministic counter-armed injections; the only sleeps are the
injected delays/hangs.  Scrapeable last stdout line::

    servechaos: faults=N recovered=M ok
"""

import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "serve")
# the overload/drain scenarios shed thousands of typed submits, each
# a serve event — uncap the rate so the control-trail assertions
# (drain / unhealthy / health transitions) cannot be rate-dropped
os.environ.setdefault("MXNET_OBS_RATE", "0")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="serve_chaos_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.observability import events as obs_events  # noqa: E402
from mxnet_tpu.observability import metrics as obs_metrics  # noqa: E402
from mxnet_tpu.resilience import chaos, servechaos  # noqa: E402
from mxnet_tpu.serve import (BucketLadder, CompiledPredictor,  # noqa: E402
                             DeadlineExceededError, DynamicBatcher,
                             ModelRegistry, OverloadError, ServeError)

DIM = 12
BUCKETS = (1, 2, 4)

failures = []       # human-readable assertion failures
all_futures = []    # every future any scenario accepted (strand sweep)
faults = 0          # injections actually fired
recovered = 0       # scenarios that fully recovered


def check(ok, msg):
    if not ok:
        failures.append(msg)
    return ok


def build_model(seed):
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="h")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="o")
    rs = np.random.RandomState(seed)
    arg_shapes, _, _ = net.infer_shape(data=(1, DIM))
    params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.1)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}
    return net, params


class RungRefs:
    """Bit-exact references: the request's rows zero-padded through
    the EAGER executor at every rung the batch could have landed on
    (tests/test_serve.py proves pad-invariance separately, so only
    the rung can change the bits)."""

    def __init__(self, net, params):
        self._net, self._params, self._execs = net, params, {}

    def refs(self, x):
        out = []
        for b in BUCKETS:
            if b < x.shape[0]:
                continue
            ex = self._execs.get(b)
            if ex is None:
                args = dict(self._params)
                args["data"] = mx.nd.array(np.zeros((b, DIM), np.float32))
                ex = self._net.bind(mx.cpu(), args)
                self._execs[b] = ex
            padded = np.zeros((b, DIM), np.float32)
            padded[:x.shape[0]] = x
            ex.arg_dict["data"][:] = mx.nd.array(padded)
            out.append(ex.forward()[0].asnumpy()[:x.shape[0]].copy())
        return out

    def matches(self, out, x):
        return any(np.array_equal(out, r) for r in self.refs(x))


def wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)   # don't busy-spin against the threads under test
    failures.append("timed out after %ss waiting for %s" % (timeout, what))
    return False


def counter_value(name):
    snap = obs_metrics.snapshot().get(name)
    return snap["value"] if snap else 0


def scenario_overload(pred, refs):
    """Slow dispatches (armed via the MXNET_CHAOS ENV spec — the
    production wire format) + a 3-request queue cap: overload sheds
    typed at submit, every accepted request completes bit-equal."""
    global faults, recovered
    chaos.reset()
    os.environ["MXNET_CHAOS"] = "slow_dispatch_ms=30"
    b = DynamicBatcher(pred, max_wait_ms=1, max_queue=3,
                       name="overload")
    try:
        shed_before = counter_value("serve_requests_shed_total")
        rs = np.random.RandomState(1)
        accepted, sheds = [], 0
        for _ in range(24):
            x = rs.randn(1, DIM).astype(np.float32)
            try:
                accepted.append((x, b.submit(x)))
            except OverloadError:
                sheds += 1
        all_futures.extend(f for _, f in accepted)
        check(sheds > 0, "overload: queue cap never shed (24 submits, "
                         "cap 3, 30ms dispatches)")
        check(b.queue_depth <= 3, "overload: queue depth %d exceeded "
                                  "its cap" % b.queue_depth)
        ok = True
        for x, fut in accepted:
            try:
                out = fut.result(30)[0]
            except Exception as e:
                ok = check(False, "overload: accepted request failed: "
                                  "%r" % (e,))
                continue
            if not refs.matches(out, x):
                ok = check(False, "overload: accepted request not "
                                  "bit-equal at any rung")
        shed_delta = counter_value("serve_requests_shed_total") \
            - shed_before
        check(shed_delta == sheds,
              "overload: serve_requests_shed_total moved %d for %d "
              "typed sheds" % (shed_delta, sheds))
        # every slowed dispatch was an injection through the env spec
        faults += b.batch_count
        if ok and sheds > 0:
            recovered += 1
    finally:
        b.close()
        del os.environ["MXNET_CHAOS"]
        chaos.reset()


def scenario_expiry(pred, refs):
    """A wedged dispatcher (dispatch_hang_at) holds the queue while a
    deadlined request expires: typed DeadlineExceededError, and the
    expired payload provably never dispatched."""
    global faults, recovered
    chaos.configure(dispatch_hang_at=1)
    servechaos.reset_hangs()
    dispatched_tags = []
    real = pred.predict

    def spy(data, key=None):
        arr = data["data"] if isinstance(data, dict) else data
        dispatched_tags.extend(np.asarray(arr)[:, 0].tolist())
        return real(data, key=key)

    pred.predict = spy
    b = DynamicBatcher(pred, max_wait_ms=1, name="expiry")
    try:
        expired_before = counter_value("serve_requests_expired_total")

        def tagged(tag):
            x = np.zeros((1, DIM), np.float32)
            x[0, 0] = tag
            return x

        filler = tagged(111.0)
        f_filler = b.submit(filler)
        all_futures.append(f_filler)
        if not wait_for(lambda: chaos.fired("dispatch_hang_at") == 1,
                        10, "the dispatcher to wedge"):
            return
        doomed = tagged(222.0)
        f_doomed = b.submit(doomed, deadline_ms=60)
        survivor = tagged(333.0)
        f_survivor = b.submit(survivor)
        all_futures.extend([f_doomed, f_survivor])
        time.sleep(0.12)                # the deadline passes, wedged
        servechaos.release_hangs()
        ok = True
        try:
            f_doomed.result(10)
            ok = check(False, "expiry: the deadlined request resolved "
                              "with a result instead of expiring")
        except DeadlineExceededError:
            pass
        except Exception as e:
            ok = check(False, "expiry: wrong error type %r" % (e,))
        for x, fut, who in ((filler, f_filler, "filler"),
                            (survivor, f_survivor, "survivor")):
            try:
                out = fut.result(10)[0]
                if not refs.matches(out, x):
                    ok = check(False, "expiry: %s not bit-equal" % who)
            except Exception as e:
                ok = check(False, "expiry: %s failed: %r" % (who, e))
        if 222.0 in dispatched_tags:
            ok = check(False, "expiry: the EXPIRED request's payload "
                              "reached a dispatch: %s" % dispatched_tags)
        check(111.0 in dispatched_tags and 333.0 in dispatched_tags,
              "expiry: expected payloads missing from dispatches: %s"
              % dispatched_tags)
        expired_delta = counter_value("serve_requests_expired_total") \
            - expired_before
        check(expired_delta == 1,
              "expiry: serve_requests_expired_total moved %d, want 1"
              % expired_delta)
        faults += chaos.fired("dispatch_hang_at")
        if ok:
            recovered += 1
    finally:
        servechaos.release_hangs()
        servechaos.reset_hangs()
        pred.predict = real
        b.close()
        chaos.reset()


def scenario_crash(pred, refs):
    """dispatch_raise_at escapes the loop: exactly the failing
    batch's futures get the error, the dispatcher restarts, the next
    batch serves."""
    global faults, recovered
    chaos.configure(dispatch_raise_at=2)
    b = DynamicBatcher(pred, max_wait_ms=1, name="crash")
    try:
        restarts_before = counter_value("serve_dispatcher_restarts_total")
        rs = np.random.RandomState(2)
        x1 = rs.randn(1, DIM).astype(np.float32)
        f1 = b.submit(x1)
        all_futures.append(f1)
        ok = True
        try:
            if not refs.matches(f1.result(30)[0], x1):
                ok = check(False, "crash: pre-crash batch not bit-equal")
        except Exception as e:
            ok = check(False, "crash: pre-crash batch failed: %r" % (e,))
        x2 = rs.randn(1, DIM).astype(np.float32)
        f2 = b.submit(x2)
        all_futures.append(f2)
        try:
            f2.result(30)
            ok = check(False, "crash: the crashing batch resolved with "
                              "a result")
        except RuntimeError as e:
            if "servechaos" not in str(e):
                ok = check(False, "crash: wrong error %r" % (e,))
        except Exception as e:
            ok = check(False, "crash: wrong error type %r" % (e,))
        if not wait_for(lambda: b.dispatcher_alive(), 10,
                        "the dispatcher to restart"):
            return
        check(b.restart_count == 1,
              "crash: restart_count %d, want 1" % b.restart_count)
        x3 = rs.randn(2, DIM).astype(np.float32)
        f3 = b.submit(x3)
        all_futures.append(f3)
        try:
            if not refs.matches(f3.result(30)[0], x3):
                ok = check(False, "crash: post-restart batch not "
                                  "bit-equal")
        except Exception as e:
            ok = check(False, "crash: post-restart batch failed: %r"
                       % (e,))
        restarts_delta = \
            counter_value("serve_dispatcher_restarts_total") \
            - restarts_before
        check(restarts_delta == 1,
              "crash: serve_dispatcher_restarts_total moved %d, want 1"
              % restarts_delta)
        faults += chaos.fired("dispatch_raise_at")
        if ok:
            recovered += 1
    finally:
        b.close()
        chaos.reset()


def scenario_unhealthy(reg):
    """Crashes past the restart budget: unhealthy, typed sheds,
    probes flip false, teardown still works."""
    global faults, recovered
    net, params = build_model(seed=3)
    reg.load("crashy", net, params, data_shapes={"data": (1, DIM)},
             ladder=BucketLadder(batches=BUCKETS))
    chaos.configure(dispatch_raise_at=1, dispatch_raise_for=10)
    b = reg.batcher("crashy", max_wait_ms=1, max_restarts=1)
    try:
        x = np.ones((1, DIM), np.float32)
        f1 = reg.submit("crashy", x)
        all_futures.append(f1)
        ok = True
        try:
            f1.result(30)
            ok = check(False, "unhealthy: crashing batch resolved")
        except (RuntimeError, ServeError):
            pass
        if not wait_for(lambda: b.restart_count >= 1 and
                        b.dispatcher_alive(), 10,
                        "the first crash-restart"):
            return
        # the restarted dispatcher crashes again on the next batch —
        # past the 1-restart budget, the batcher goes unhealthy
        f2 = reg.submit("crashy", x)
        all_futures.append(f2)
        try:
            f2.result(30)
            ok = check(False, "unhealthy: post-budget submit "
                              "resolved with a result")
        except (RuntimeError, ServeError):
            pass
        if not wait_for(lambda: b.unhealthy, 10,
                        "the batcher to exhaust its restart budget"):
            return
        try:
            reg.submit("crashy", x)
            ok = check(False, "unhealthy: submit to an unhealthy "
                              "batcher did not shed")
        except ServeError:
            pass
        check(b.health_state() == "unhealthy",
              "unhealthy: health_state %r" % b.health_state())
        check(reg.health("crashy")["state"] == "unhealthy",
              "unhealthy: registry health %r"
              % reg.health("crashy")["state"])
        check(reg.ready("crashy") is False,
              "unhealthy: ready() still true")
        check(reg.live() is False, "unhealthy: live() still true")
        faults += chaos.fired("dispatch_raise_at")
        reg.unload("crashy", drain=False)
        check(reg.live() is True,
              "unhealthy: live() still false after unload")
        if ok:
            recovered += 1
    finally:
        chaos.reset()
        if "crashy" in reg.names():
            reg.unload("crashy", drain=False)


def scenario_liveness(reg):
    """A wedged dispatch with work queued goes stale on the health
    surface; releasing it recovers, and both requests land."""
    global faults, recovered
    net, params = build_model(seed=4)
    refs = RungRefs(net, params)
    reg.load("hangy", net, params, data_shapes={"data": (1, DIM)},
             ladder=BucketLadder(batches=BUCKETS))
    chaos.configure(dispatch_hang_at=1)
    servechaos.reset_hangs()
    reg.batcher("hangy", max_wait_ms=1)
    try:
        rs = np.random.RandomState(5)
        x1 = rs.randn(1, DIM).astype(np.float32)
        f1 = reg.submit("hangy", x1)
        all_futures.append(f1)
        if not wait_for(lambda: chaos.fired("dispatch_hang_at") == 1,
                        10, "the dispatcher to wedge"):
            return
        x2 = rs.randn(1, DIM).astype(np.float32)
        f2 = reg.submit("hangy", x2)      # queued behind the wedge
        all_futures.append(f2)
        time.sleep(0.25)
        ok = check(reg.live(max_tick_age=0.2) is False,
                   "liveness: a wedged dispatcher with queued work "
                   "still probes live")
        health = reg.health("hangy")
        check(health["queue_depth"] >= 1,
              "liveness: queue_depth %d with a request queued behind "
              "the wedge" % health["queue_depth"])
        servechaos.release_hangs()
        for x, fut, who in ((x1, f1, "wedged"), (x2, f2, "queued")):
            try:
                out = fut.result(30)[0]
                if not refs.matches(out, x):
                    ok = check(False, "liveness: %s request not "
                                      "bit-equal" % who)
            except Exception as e:
                ok = check(False, "liveness: %s request failed: %r"
                           % (who, e))
        if not wait_for(lambda: reg.live(max_tick_age=5.0), 10,
                        "liveness to recover after release"):
            return
        faults += chaos.fired("dispatch_hang_at")
        if ok:
            recovered += 1
    finally:
        servechaos.release_hangs()
        servechaos.reset_hangs()
        chaos.reset()
        reg.unload("hangy", drain=False)


def scenario_drain(reg):
    """unload(drain=True) under concurrent submit load with slow
    dispatches: every ACCEPTED request completes bit-equal at some
    rung, later submits shed typed, nothing hangs."""
    global faults, recovered
    net, params = build_model(seed=6)
    refs = RungRefs(net, params)
    reg.load("prime", net, params, data_shapes={"data": (1, DIM)},
             ladder=BucketLadder(batches=BUCKETS))
    chaos.configure(slow_dispatch_ms=20)
    b = reg.batcher("prime", max_wait_ms=1)
    drains_before = counter_value("serve_drains_total")
    rs = np.random.RandomState(7)
    pool = [rs.randn(1, DIM).astype(np.float32) for _ in range(8)]
    accepted, untyped = [], []
    stop = threading.Event()

    def writer(tid):
        i = 0
        while not stop.is_set():
            x = pool[(tid + i) % len(pool)]
            i += 1
            try:
                accepted.append((x, reg.submit("prime", x)))
            except ServeError:
                pass                    # draining / unloaded: typed
            except Exception as e:
                untyped.append(repr(e))
                return

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.15)                # queue backs up behind 20ms
        reg.unload("prime")             # drain=True default
        stop.set()
        for t in threads:
            t.join(10)
            check(not t.is_alive(), "drain: a writer thread hung")
        all_futures.extend(f for _, f in accepted)
        check(untyped == [], "drain: untyped writer errors: %s"
              % untyped[:3])
        ok = True
        completed = 0
        for x, fut in accepted:
            try:
                out = fut.result(10)[0]
            except ServeError:
                continue                # shed/closed: typed is fine
            except Exception as e:
                ok = check(False, "drain: untyped failure %r" % (e,))
                continue
            completed += 1
            if not refs.matches(out, x):
                ok = check(False, "drain: accepted request not "
                                  "bit-equal at any rung")
        check(completed >= 1, "drain: no request completed (%d "
                              "accepted)" % len(accepted))
        drains_delta = counter_value("serve_drains_total") \
            - drains_before
        check(drains_delta == 1,
              "drain: serve_drains_total moved %d, want 1"
              % drains_delta)
        faults += b.batch_count         # every dispatch was slowed
        if ok and completed >= 1:
            recovered += 1
    finally:
        stop.set()
        chaos.reset()
        if "prime" in reg.names():
            reg.unload("prime", drain=False)


def scenario_warm(reg):
    """reject_warm_at fails a load mid-warm: the model never
    half-registers; the retried load serves."""
    global faults, recovered
    net, params = build_model(seed=8)
    chaos.configure(reject_warm_at=2)   # the 2nd program build dies
    ok = True
    try:
        reg.load("flaky", net, params, data_shapes={"data": (1, DIM)},
                 ladder=BucketLadder(batches=BUCKETS))
        ok = check(False, "warm: injected warm failure did not raise")
    except ServeError:
        pass
    check("flaky" not in reg.names(),
          "warm: a failed load half-registered the model")
    check(reg.ready("flaky") is False,
          "warm: a failed load left a health entry")
    faults += chaos.fired("reject_warm_at")
    chaos.reset()
    reg.load("flaky", net, params, data_shapes={"data": (1, DIM)},
             ladder=BucketLadder(batches=BUCKETS))
    refs = RungRefs(net, params)
    x = np.random.RandomState(9).randn(1, DIM).astype(np.float32)
    fut = reg.submit("flaky", x)
    all_futures.append(fut)
    try:
        if not refs.matches(fut.result(30)[0], x):
            ok = check(False, "warm: retried load serves wrong bits")
    except Exception as e:
        ok = check(False, "warm: retried load failed to serve: %r"
                   % (e,))
    check(reg.ready("flaky") is True, "warm: retried load not ready")
    reg.unload("flaky", drain=False)
    if ok:
        recovered += 1


def check_health_trail():
    """The state machine walked its full cycle, replayable from
    events.jsonl."""
    evs = obs_events.read_events()
    trails = {}
    for e in evs:
        if e.get("ev") == "serve" and e.get("kind") == "health":
            trails.setdefault(e["model"], []).append(e["state"])
    prime = trails.get("prime", [])
    for a, b in (("loading", "warming"), ("warming", "ready"),
                 ("ready", "draining")):
        if not (a in prime and b in prime and
                prime.index(a) < prime.index(b)):
            failures.append("health trail for 'prime' lacks %s->%s: %s"
                            % (a, b, prime))
    crashy = trails.get("crashy", [])
    if "unhealthy" not in crashy:
        failures.append("health trail for 'crashy' lacks unhealthy: %s"
                        % crashy)
    kinds = {e.get("kind") for e in evs if e.get("ev") == "serve"}
    for kind in ("shed", "expired", "dispatcher_restart", "unhealthy",
                 "drain", "load_failed", "health"):
        if kind not in kinds:
            failures.append("serve event kind %r never recorded "
                            "(have %s)" % (kind, sorted(kinds)))


def check_no_stranded():
    """Every future any scenario accepted resolved — with a result or
    a typed error, never a hang."""
    stranded = 0
    for fut in all_futures:
        if not fut._event.wait(5):
            stranded += 1
    if stranded:
        failures.append("%d of %d accepted futures never resolved"
                        % (stranded, len(all_futures)))


def main():
    t0 = time.monotonic()
    obs_events.configure(path=os.environ["MXNET_OBS_PATH"])
    net, params = build_model(seed=0)
    pred = CompiledPredictor(net, params,
                             data_shapes={"data": (1, DIM)},
                             ladder=BucketLadder(batches=BUCKETS),
                             name="shared")
    pred.warm()
    refs = RungRefs(net, params)
    reg = ModelRegistry()
    try:
        scenario_overload(pred, refs)
        scenario_expiry(pred, refs)
        scenario_crash(pred, refs)
        scenario_unhealthy(reg)
        scenario_liveness(reg)
        scenario_drain(reg)
        scenario_warm(reg)
    finally:
        chaos.reset()
        reg.close()
    check_no_stranded()
    check_health_trail()
    for f in failures:
        print("serve chaos FAILURE: %s" % f, file=sys.stderr)
    print("servechaos: faults=%d recovered=%d/7 futures=%d %.1fs %s"
          % (faults, recovered, len(all_futures),
             time.monotonic() - t0, "FAIL" if failures else "ok"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
