#!/usr/bin/env bash
# CI entry point (reference: ci/docker/runtime_functions.sh sanity + unit
# test functions).  Runs the full suite on the virtual 8-device CPU mesh,
# byte-compiles the package as a lint floor, and builds the C predict ABI.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== sanity: byte-compile =="
python -m compileall -q mxnet_tpu tools examples

echo "== sanity: graftlint static analysis =="
# Pure-stdlib AST pass (no jax import, no accelerator needed, <10s):
# tracer leaks, donation misuse, recompile hazards, registry contract.
# Exits nonzero on any finding not in tools/graftlint/baseline.json;
# the last stdout line is the scrapeable summary ("graftlint: ...").
python -m tools.graftlint mxnet_tpu

echo "== graftir: StableHLO program audit + manifest gate =="
# Lowers the representative AOT program set (fused step, serve rungs,
# decode tick/prefill, quantized rung) on CPU avals and audits the
# StableHLO text: rules GI001-GI005 (donation coverage, dtype policy,
# host round-trips, pad-waste, program budgets) against the committed
# baseline, plus the committed per-program cost manifest
# (tools/graftir/manifest.json — >10% flops/bytes growth or program-
# count drift fails; --update-manifest to accept an intended change).
# The smoke also proves the auditor still CATCHES seeded regressions
# (2x cost, stripped donation, injected f64).  Seconds, CPU-only
# (docs/ir_audit.md).  Last stdout line is the scrapeable summary
# ("graftir: programs=.. findings=.. ok").
MXNET_SAN=all python ci/graftir_smoke.py
python -m tools.graftir --check

echo "== graftsan: sanitizer-enabled smoke train step =="
# Fused + partial-fused train steps, PrefetchingIter, local kvstore
# with ALL FOUR runtime sanitizers on (race/lockset + lock-order,
# recompile-blame, use-after-donate poison, host-transfer guard).
# Fails on any sanitizer report or a broken one-program-per-step
# contract.  Seconds, CPU-only (docs/sanitizers.md).
MXNET_SAN=all python ci/graftsan_smoke.py

echo "== graftsched: deterministic schedule exploration drill =="
# Serializing-scheduler model check of the threaded serving/kvstore
# subsystems: every shipped scenario explores its bounded schedule
# set (preemption bounding + DPOR pruning) with zero findings, the
# seeded PR-19 stop() double-teardown is re-found and its trace
# replays bit-exactly, and the graftsched counters move.  Seconds,
# CPU-only (docs/sanitizers.md "Schedule exploration").  Last stdout
# line: "graftsched: scenarios=.. schedules=.. findings=0 ok".
MXNET_SAN=sched python ci/sched_drill.py

echo "== observability: telemetry smoke train step =="
# Short fused-step run with MXNET_OBS=all: asserts the expected
# instruments exist with sane values, events.jsonl is well-formed
# (gapless seq, compile event present), and profiler.dump() carries
# the registry counters next to its spans.  Seconds, CPU-only; last
# stdout line is the scrapeable summary ("obs: instruments=.. ...").
MXNET_OBS=all python ci/obs_smoke.py

echo "== perf: input-pipeline overlap smoke (device prefetch + async guard) =="
# Host-bound iterator (X ms decode) + real fused steps (Y ms): the
# DevicePrefetcher ring + MXNET_GUARD_READBACK_LAG async guard
# accounting must reach a steady state of ~max(X,Y) per step vs the
# serial path's X+Y (asserted < 0.7x serial), with zero graftsan
# reports from the ring's threads/locks and the input-wait/stall
# instruments live.  Seconds, CPU-only (docs/perf_input_pipeline.md).
# Last stdout line is the scrapeable summary ("inputperf: ... ok").
MXNET_SAN=all python ci/input_overlap_smoke.py

echo "== serve: compiled-inference smoke (registry + dynamic batcher) =="
# Two-model registry under concurrent mixed-size traffic through the
# dynamic batcher, sanitizers on: asserts one AOT compile per bucket
# and ZERO compiles/traces in the request path, every caller's rows
# bit-equal to the eager forward at some rung, p50/p99 emitted from
# the request histogram, and no graftsan reports from the batcher's
# locks/threads.  Seconds, CPU-only (docs/serving.md).  Last stdout
# line is the scrapeable summary ("serve: reqs=.. batches=.. ...").
MXNET_SAN=all python ci/serve_smoke.py

echo "== serve: continuous-batching decode drill (paged KV pool) =="
# Sixteen staggered decode sessions through the paged KV pool and the
# continuous-batching tick loop, sanitizers on: every session's token
# stream bit-equal to its SOLO dense-cache decode (block-table
# gather/scatter, co-tenant garbage, rung padding and join/leave
# churn invisible in the tokens), one AOT compile per tick/prefill
# rung and ZERO in the request path, a mid-decode cancel keeping its
# accepted tokens, typed KVPoolExhausted shedding + recovery, a
# chaos-armed tick crash surviving quarantine-and-rebuild (fresh pool
# against warm programs, journaled sessions re-admitted bit-equal,
# past-budget crash failing typed), zero leaked blocks, zero graftsan
# reports (docs/serving.md).  Last stdout line:
# "decode: sessions=.. ticks=.. compiles=.. rebuilds=.. ok".
MXNET_SAN=all python ci/decode_smoke.py

echo "== perf: autotune smoke (measured search + store pickup) =="
# A real successive-halving search over the serve knob space against
# a short synthetic trace (tiny FC model, ~8 candidates, analytic-
# prior pruning), sanitizers on: asserts the search completes, the
# winner is never worse than the measured default on the same trace
# (baseline guard), zero request-path compiles in every replay, the
# TuningStore round-trips with the trace identity + measurement
# artifact, and a fresh registry under MXNET_TUNING_STORE applies
# the winning config and serves the same trace with zero request-
# path compiles (docs/autotuning.md).  Last stdout line is the
# scrapeable summary ("autotune: trials=.. pruned=.. ...").
MXNET_SAN=all python ci/autotune_smoke.py

echo "== perf: quantized-serving smoke (calibrate/lower/gate/serve) =="
# The int8 post-training quantization pipeline end to end, sanitizers
# on: calibrate a conv+FC model on synthetic batches, atomic calib-
# table round-trip (a corrupted table fails the load typed), quantize
# and load through ModelRegistry with the accuracy gate enforced at
# every rung (an impossible threshold fails typed), int8 dot/conv ops
# asserted present in every rung's lowered StableHLO, concurrent
# mixed-size traffic through a real DynamicBatcher with zero request-
# path compiles, balanced quantize events, instruments moving, zero
# graftsan reports (docs/quantization.md).  Last stdout line:
# "quant: layers=.. covered=.. acc_ok compiles=0 ok".
MXNET_SAN=all python ci/quant_smoke.py

echo "== serve: request-path chaos drill (shedding/supervision/drain) =="
# The serving request path through every injected fault class —
# overload (slow dispatches vs a bounded queue), deadline expiry
# under a wedged dispatcher, dispatcher crash + restart, restart-
# budget exhaustion to unhealthy, stale-liveness detection, drain-
# under-load, and a failed warm compile: asserts typed errors only,
# zero stranded futures, expired payloads provably never dispatched,
# drained requests bit-equal to eager at some rung, and the health
# state machine replayable from events.jsonl (docs/serving.md).
# Deterministic counter-armed injections; the only sleeps are the
# injected delays/hangs.  Last stdout line is the scrapeable summary
# ("servechaos: faults=.. recovered=.. ok").
python ci/serve_chaos_drill.py

echo "== serve: fleet chaos drill (3 replicas, kill/deploy/partition) =="
# Three REAL replica processes behind the router under concurrent
# load: a replica hard-killed mid-request (router failover, same
# request id, dedup window), a drain-aware rolling deploy to a new
# checkpoint (zero dropped accepted requests, successors warm from
# the shared persistent XLA compile cache with zero new entries and
# zero request-path compiles), and a router<->replica partition
# (breaker opens, staleness ejects, healing rejoins).  Every accepted
# request is answered bit-equal to the eager forward at some
# rung/version or fails typed — never lost, never hung; bounded
# child-process cleanup on failure (docs/serving.md "Serving
# fleet").  Last stdout line is the scrapeable summary
# ("fleet: replicas=.. faults=.. recovered=.. ok").
MXNET_SAN=all python ci/fleet_chaos_drill.py

echo "== resilience: chaos-injected fault drills =="
# The resilience suite under the chaos harness: kill-mid-save,
# corrupt-checkpoint, NaN-step, and preemption drills against the REAL
# checkpoint/guard/fit code paths.  Deterministic counters + injected
# backoff clocks — no sleeps, seconds not minutes (docs/resilience.md).
MXNET_CHAOS=on python -m pytest tests/test_resilience.py -q \
    -p no:cacheprovider

echo "== resilience: network chaos drill (dist kvstore) =="
# Real 2-worker x 2-server dist_sync jobs through every injected
# network fault class — drop / delay / duplicate / torn-frame /
# partition / server-kill / dead-worker: asserts convergence-
# equivalent pulls, exactly-once apply counters, snapshot-restore
# after a hard kill, and eviction unblocking the survivors.
# Deterministic counter-armed injections; the only sleeps are the
# injected delays (docs/resilience.md).  The elastic scenarios follow
# (grow/shrink/evict+replace/3->2->4 resize chain under load:
# exactly-once coverage, zero lost accepted pushes, convergence
# equivalence vs the fixed-size baseline — docs/resilience.md
# "Elastic training").  Last stdout lines are the scrapeable
# summaries ("elastic: resizes=.. joins=.. evictions=.. ok" then
# "netchaos: faults=.. recovered=.. ok").
python ci/netchaos_drill.py

echo "== resilience: crash-anywhere drill (supervisor + watchdog) =="
# A supervised training job hard-killed at seeded ARBITRARY steps
# (plus one injected hang the watchdog must catch and flight-record)
# auto-resumes from per-batch job-state checkpoints and finishes
# BIT-IDENTICAL to an uninterrupted run — params, optimizer state,
# metric — with zero replayed or skipped batches (per-batch sequence
# log), and events.jsonl keeps a monotone seq across every restart.
# Last stdout line: "crash_anywhere: kills=.. hangs=.. ... ok".
python ci/crash_anywhere_drill.py

echo "== native: C predict ABI + RecordIO reader =="
if command -v g++ >/dev/null; then
    make -C src/capi
    make -C src/io
else
    echo "g++ not found — skipping native build"
fi

echo "== unit tests (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q "$@"
