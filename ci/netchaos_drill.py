#!/usr/bin/env python
"""Network-chaos drill: real 2-worker x 2-server dist_sync training
jobs driven through every injected network fault class, asserting
convergence-equivalent results and exactly-once push application.

Fault classes (deterministic counter-armed injections; the only
sleeps are the injected delays — see mxnet_tpu/resilience/netchaos.py
and docs/resilience.md "Distributed fault tolerance"):

  baseline       no faults — the reference pull values
  worker_faults  net_partition + net_dup_request + net_delay_request
  drop_reply     server computes the push, drops the reply: the
                 worker's RPC timeout + retried request id must dedup
  delay_reply    reply delayed BEYOND the worker RPC timeout: full
                 timeout -> reconnect -> retry -> dedup path
  torn           half-frames in both directions (request + reply)
  server_kill    server 0 hard-killed (os._exit 137) mid-run, then
                 restarted: must restore its state snapshot; retried
                 pushes apply exactly once across incarnations
  eviction       worker 1 dies without ceremony: its stale heartbeat
                 gets it evicted and worker 0 finishes alone

Every class asserts: worker exit 0, the expected per-step pull values,
and per-server ``applies == steps * keys-on-server`` — the server-side
apply counter equaling the logical rounds IS the exactly-once proof
(a double-applied retry or duplicate breaks it).

Scrapeable last stdout line:  netchaos: faults=N recovered=M ok
"""

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
STEPS = 3
N_WORKERS = 2
N_SERVERS = 2
BIG_BOUND = 10          # "big" has 24 elements -> sharded over both

WORKER = r'''
import os, sys, json
sys.path.insert(0, os.environ["NC_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.resilience import chaos

rank = int(os.environ["DMLC_WORKER_RANK"])
steps = int(os.environ["NC_STEPS"])
die_after = int(os.environ.get("NC_DIE_AFTER_STEP", "0"))
kv = mx.kv.create("dist_sync")
kv.init("w", nd.zeros((4,)))
kv.init("big", nd.zeros((24,)))      # > bound -> sharded, both servers
results = []
for step in range(1, steps + 1):
    kv.push("w", nd.ones((4,)) * (rank + 1))
    kv.push("big", nd.ones((24,)) * (rank + 1))
    kv.barrier()
    out_w = nd.zeros((4,))
    out_b = nd.zeros((24,))
    kv.pull("w", out=out_w)
    kv.pull("big", out=out_b)
    results.append([float(out_w.asnumpy()[0]),
                    float(out_b.asnumpy()[0])])
    if die_after and rank == 1 and step >= die_after:
        os._exit(0)    # crash: no barrier, no stop, heartbeats cease
    kv.barrier()
print("RESULT", rank, json.dumps(results), flush=True)
print("CHAOSFIRED", rank, json.dumps({k: chaos.fired(k) for k in
      ("net_partition", "net_delay_request", "net_dup_request",
       "net_torn_request")}), flush=True)
if rank == 0:
    stats = [kv.server_stats(server=s)
             for s in range(int(os.environ.get("DMLC_NUM_SERVER", "1")))]
    print("STATS", json.dumps(stats), flush=True)
kv.barrier()
if rank == 0:
    kv.stop_server()
'''

SERVER = r'''
import os, sys, json
sys.path.insert(0, os.environ["NC_REPO"])
from mxnet_tpu.kvstore_server import run_server
from mxnet_tpu.resilience import chaos
run_server("dist_sync")
print("CHAOSFIRED", json.dumps({k: chaos.fired(k) for k in
      ("net_drop_reply", "net_delay_reply", "net_torn_reply")}),
      flush=True)
'''


def _spec(d):
    return ",".join("%s=%d" % (k, v) for k, v in sorted(d.items()))


def _spawn_server(env, sid, server_chaos):
    senv = dict(env, DMLC_ROLE="server", DMLC_SERVER_ID=str(sid),
                # suppress the package's server re-exec bootstrap: this
                # wrapper must regain control after run_server returns
                # to report which injections actually fired
                _MXTPU_SERVER_BOOT="1")
    if server_chaos:
        senv["MXNET_CHAOS"] = _spec(server_chaos)
    return subprocess.Popen([PY, "-c", SERVER], env=senv,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)


def run_class(name, **kw):
    """One 2x2 dist_sync job under a fault class; returns the number
    of injections observed fired across all processes.  Never leaks
    children: a failed assertion kills every spawned process so later
    classes' ports stay free."""
    procs = []
    try:
        return _run_class(name, procs, **kw)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _run_class(name, procs, worker_chaos=None, server_chaos=None,
               worker_env=None, server_env=None, die_after=0,
               kill_server0=False, port=9610):
    snapdir = tempfile.mkdtemp(prefix="netchaos_%s_" % name)
    env = dict(os.environ)
    env.pop("MXNET_CHAOS", None)
    env.update({
        "NC_REPO": REPO,
        "NC_STEPS": str(STEPS),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(N_WORKERS),
        "DMLC_NUM_SERVER": str(N_SERVERS),
        "MXNET_KVSTORE_BIGARRAY_BOUND": str(BIG_BOUND),
        "MXNET_KVSTORE_SNAPSHOT_PREFIX": os.path.join(snapdir, "snap"),
        "MXNET_KVSTORE_SNAPSHOT_EVERY": "1",
        "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "0.2",
        "JAX_PLATFORMS": "cpu",
    })
    env.update(server_env or {})
    servers = []
    for sid in range(N_SERVERS):
        chaos_for = dict(server_chaos or {})
        if kill_server0 and sid == 0:
            # the kill switch arms ONLY server 0's first incarnation
            chaos_for["net_kill_server_at"] = 3
        servers.append(_spawn_server(env, sid, chaos_for))
    procs.extend(servers)
    wenv_base = dict(env)
    wenv_base.update(worker_env or {})
    wenv_base.setdefault("MXNET_KVSTORE_RPC_TIMEOUT", "4")
    wenv_base.setdefault("MXNET_KVSTORE_RPC_RETRIES", "8")
    if worker_chaos:
        wenv_base["MXNET_CHAOS"] = _spec(worker_chaos)
    workers = []
    for rank in range(N_WORKERS):
        wenv = dict(wenv_base, DMLC_ROLE="worker",
                    DMLC_WORKER_RANK=str(rank))
        if die_after:
            wenv["NC_DIE_AFTER_STEP"] = str(die_after)
        workers.append(subprocess.Popen([PY, "-c", WORKER], env=wenv,
                                        stdout=subprocess.PIPE,
                                        stderr=subprocess.PIPE))
    procs.extend(workers)
    fired = 0
    if kill_server0:
        # wait for the injected hard kill, then restart the server on
        # the same port + snapshot prefix WITHOUT the kill switch
        deadline = time.time() + 90
        while servers[0].poll() is None and time.time() < deadline:
            time.sleep(0.1)
        rc = servers[0].poll()
        assert rc == 137, \
            "server 0 should have been hard-killed, rc=%r" % (rc,)
        fired += 1
        servers[0] = _spawn_server(env, 0, server_chaos or {})
        procs.append(servers[0])
        print("  server 0 killed (rc=137) and restarted", flush=True)

    outs = []
    for w in workers:
        stdout, stderr = w.communicate(timeout=180)
        assert w.returncode == 0, \
            "[%s] worker failed:\n%s" % (name, stderr.decode()[-3000:])
        outs.append(stdout.decode())

    # -- value assertions: convergence-equivalent pulls ------------------
    # sync + no updater => pulled value = the round's aggregated sum
    both = float(sum(r + 1 for r in range(N_WORKERS)))     # 3.0
    for out in outs:
        lines = out.splitlines()
        res = [l for l in lines if l.startswith("RESULT")]
        if not res:
            assert die_after, "[%s] missing RESULT:\n%s" % (name, out)
            continue            # the deliberately-dead worker
        rank = int(res[0].split(" ", 2)[1])
        vals = json.loads(res[0].split(" ", 2)[2])
        for step, (w_val, b_val) in enumerate(vals, 1):
            if die_after and step > die_after:
                want = 1.0      # only worker 0 contributes post-evict
            else:
                want = both
            assert w_val == want and b_val == want, \
                "[%s] rank %d step %d: got (%s, %s), want %s" \
                % (name, rank, step, w_val, b_val, want)
        for l in lines:
            if l.startswith("CHAOSFIRED"):
                fired += sum(json.loads(l.split(" ", 2)[2]).values())

    # -- exactly-once: server apply counters match logical rounds --------
    stats_line = [l for o in outs for l in o.splitlines()
                  if l.startswith("STATS")]
    assert stats_line, "[%s] rank 0 printed no STATS" % name
    stats = json.loads(stats_line[0].split(" ", 1)[1])
    for st in stats:
        nkeys = len(st["keys"])
        assert nkeys >= 1, "[%s] server %s lost every key: %s" \
            % (name, st["server_id"], st)
        assert st["applies"] == STEPS * nkeys, \
            "[%s] server %s: applies=%d != steps*keys=%d (%s) — " \
            "retry/duplicate was NOT exactly-once" \
            % (name, st["server_id"], st["applies"], STEPS * nkeys, st)
        if die_after:
            assert 1 in st["evicted"], \
                "[%s] server %s never evicted dead rank 1: %s" \
                % (name, st["server_id"], st)
    if kill_server0:
        assert stats[0]["snapshots"] >= 1, \
            "[%s] restarted server 0 never snapshotted: %s" \
            % (name, stats[0])

    for i, s in enumerate(servers):
        try:
            sout, serr = s.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            s.kill()
            raise AssertionError("[%s] server %d did not stop" % (name, i))
        assert s.returncode == 0, \
            "[%s] server %d rc=%d:\n%s" % (name, i, s.returncode,
                                           serr.decode()[-2000:])
        for l in sout.decode().splitlines():
            if l.startswith("CHAOSFIRED"):
                fired += sum(json.loads(l.split(" ", 1)[1]).values())
    if die_after:
        fired += 1              # the real worker death is the fault
    return fired


def main():
    classes = [
        ("baseline", {}),
        ("worker_faults", dict(
            worker_chaos={"net_partition": 2, "net_dup_request": 2,
                          "net_delay_request": 2, "net_delay_ms": 100})),
        ("drop_reply", dict(
            server_chaos={"net_drop_reply": 2},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("delay_reply", dict(
            # delay > RPC timeout: the worker must ride the full
            # timeout -> reconnect -> retry -> dedup path
            server_chaos={"net_delay_reply": 1, "net_delay_ms": 3500},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("torn", dict(
            worker_chaos={"net_torn_request": 2},
            server_chaos={"net_torn_reply": 1},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("server_kill", dict(kill_server0=True)),
        ("eviction", dict(
            die_after=1,
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "10"},
            server_env={"MXNET_KVSTORE_SYNC_TIMEOUT": "3",
                        "MXNET_KVSTORE_EVICT_TIMEOUT": "1.0"})),
    ]
    total_fired = 0
    recovered = 0
    for i, (name, kw) in enumerate(classes):
        t0 = time.time()
        print("== netchaos class: %s ==" % name, flush=True)
        fired = run_class(name, port=9610 + 10 * i, **kw)
        if name != "baseline":
            assert fired > 0, \
                "[%s] armed faults never fired — the drill is inert" \
                % name
            recovered += 1
        total_fired += fired
        print("  ok (%d injections, %.1fs)" % (fired, time.time() - t0),
              flush=True)
    print("netchaos: faults=%d recovered=%d ok"
          % (total_fired, recovered), flush=True)


if __name__ == "__main__":
    main()
