#!/usr/bin/env python
"""Network-chaos drill: real 2-worker x 2-server dist_sync training
jobs driven through every injected network fault class, asserting
convergence-equivalent results and exactly-once push application.

Fault classes (deterministic counter-armed injections; the only
sleeps are the injected delays — see mxnet_tpu/resilience/netchaos.py
and docs/resilience.md "Distributed fault tolerance"):

  baseline       no faults — the reference pull values
  worker_faults  net_partition + net_dup_request + net_delay_request
  drop_reply     server computes the push, drops the reply: the
                 worker's RPC timeout + retried request id must dedup
  delay_reply    reply delayed BEYOND the worker RPC timeout: full
                 timeout -> reconnect -> retry -> dedup path
  torn           half-frames in both directions (request + reply)
  server_kill    server 0 hard-killed (os._exit 137) mid-run, then
                 restarted: must restore its state snapshot; retried
                 pushes apply exactly once across incarnations
  eviction       worker 1 dies without ceremony: its stale heartbeat
                 gets it evicted and worker 0 finishes alone

Every class asserts: worker exit 0, the expected per-step pull values,
and per-server ``applies == steps * keys-on-server`` — the server-side
apply counter equaling the logical rounds IS the exactly-once proof
(a double-applied retry or duplicate breaks it).

Scrapeable last stdout line:  netchaos: faults=N recovered=M ok
"""

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)    # the elastic driver imports mxnet_tpu
PY = sys.executable
STEPS = 3
N_WORKERS = 2
N_SERVERS = 2
BIG_BOUND = 10          # "big" has 24 elements -> sharded over both

WORKER = r'''
import os, sys, json
sys.path.insert(0, os.environ["NC_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.resilience import chaos

rank = int(os.environ["DMLC_WORKER_RANK"])
steps = int(os.environ["NC_STEPS"])
die_after = int(os.environ.get("NC_DIE_AFTER_STEP", "0"))
kv = mx.kv.create("dist_sync")
kv.init("w", nd.zeros((4,)))
kv.init("big", nd.zeros((24,)))      # > bound -> sharded, both servers
results = []
for step in range(1, steps + 1):
    kv.push("w", nd.ones((4,)) * (rank + 1))
    kv.push("big", nd.ones((24,)) * (rank + 1))
    kv.barrier()
    out_w = nd.zeros((4,))
    out_b = nd.zeros((24,))
    kv.pull("w", out=out_w)
    kv.pull("big", out=out_b)
    results.append([float(out_w.asnumpy()[0]),
                    float(out_b.asnumpy()[0])])
    if die_after and rank == 1 and step >= die_after:
        os._exit(0)    # crash: no barrier, no stop, heartbeats cease
    kv.barrier()
print("RESULT", rank, json.dumps(results), flush=True)
print("CHAOSFIRED", rank, json.dumps({k: chaos.fired(k) for k in
      ("net_partition", "net_delay_request", "net_dup_request",
       "net_torn_request")}), flush=True)
if rank == 0:
    stats = [kv.server_stats(server=s)
             for s in range(int(os.environ.get("DMLC_NUM_SERVER", "1")))]
    print("STATS", json.dumps(stats), flush=True)
kv.barrier()
if rank == 0:
    kv.stop_server()
'''

SERVER = r'''
import os, sys, json
sys.path.insert(0, os.environ["NC_REPO"])
from mxnet_tpu.kvstore_server import run_server
from mxnet_tpu.resilience import chaos
run_server("dist_sync")
print("CHAOSFIRED", json.dumps({k: chaos.fired(k) for k in
      ("net_drop_reply", "net_delay_reply", "net_torn_reply")}),
      flush=True)
'''


# ---------------------------------------------------------------------------
# Elastic scenarios (ROADMAP item 7 / docs/resilience.md "Elastic
# training"): real dist_sync SGD jobs (linear regression, two param
# keys sharded across the servers' hash space) that shrink, grow, and
# resize N->M under load WITHOUT a restart.  Asserts per scenario:
#   * exactly-once sample coverage per epoch across every resize
#     (the workers log the global indices they consumed; the driver
#     unions them) — skipped only where a worker is hard-killed,
#   * zero lost accepted pushes: per-server applies == completed
#     rounds x keys-on-server,
#   * every completing worker pulled the SAME final weights,
#   * convergence equivalence: the elastic run's final MSE within
#     tolerance of the fixed-size baseline's,
#   * retired ranks exit rc 0 printing RETIRED; joiners are admitted
#     and consume their shard.
# Scrapeable: "elastic: resizes=N joins=M evictions=K ok" before the
# final netchaos summary line.
# ---------------------------------------------------------------------------

ELASTIC_WORKER = r'''
import os, sys, json, time
sys.path.insert(0, os.environ["NC_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import NDArrayIter

rank = int(os.environ["DMLC_WORKER_RANK"])
EPOCHS = int(os.environ.get("EW_EPOCHS", "3"))
joiner = os.environ.get("EW_JOINER") == "1"
die_rank = int(os.environ.get("EW_DIE_RANK", "-1"))
die_round = int(os.environ.get("EW_DIE_AFTER_ROUND", "0"))
round_sleep = float(os.environ.get("EW_ROUND_SLEEP", "0"))
N, D, B, LR, SEED = 48, 4, 2, 0.12, 13
rs = np.random.RandomState(7)
X = rs.randn(N, D)
w_true = rs.randn(D)
y = X @ w_true

def make_iter(pos, active):
    return NDArrayIter({"data": X.astype(np.float32)},
                       {"label": y.astype(np.float32)}, batch_size=B,
                       shuffle=True, shuffle_seed=SEED,
                       last_batch_handle="pad",
                       part_index=pos, num_parts=active)

kv = mx.kv.create("dist_sync")
if not joiner:
    kv.init("wa", nd.zeros((2,)))
    kv.init("wb", nd.zeros((2,)))
    kv.set_optimizer(mx.optimizer.create(
        "sgd", learning_rate=1.0, rescale_grad=1.0, wd=0.0))
    view = kv.membership()
    members, mep = sorted(view["members"]), view["mep"]
    pos, active = members.index(rank), len(members)
    it = make_iter(pos, active)
    epoch = 0
else:
    kv.wait_admission()
    admitted_round = kv._barrier_round
    # take the shard assignment from the job metadata the survivors
    # published at (or after) the admission round: that is the EXACT
    # member list they re-sharded under — a fresh stats read could
    # already include a later admission they have not re-sharded for
    deadline = time.monotonic() + 90
    while True:
        meta = kv.get_job_meta()
        if meta and meta.get("round", -1) >= admitted_round \
                and rank in meta.get("members", ()):
            break
        assert time.monotonic() < deadline, "joiner: no job metadata"
        time.sleep(0.1)
    members, mep = sorted(meta["members"]), meta["mep"]
    pos, active = members.index(rank), len(members)
    it = make_iter(0, 1)
    it.load_state(meta["data"])
    it.repartition(pos, active)
    epoch = int(meta["epoch"])
    print("JOINED", rank, json.dumps({"round": admitted_round,
                                      "epoch": epoch}), flush=True)

out_a, out_b = nd.zeros((2,)), nd.zeros((2,))
kv.pull("wa", out=out_a)
kv.pull("wb", out=out_b)
w = np.concatenate([out_a.asnumpy(), out_b.asnumpy()]).astype(np.float64)
consumed = []          # [epoch, [global indices]] per batch
accepted = 0           # pushes acknowledged (rounds participated)
retired = False
while epoch < EPOCHS and not retired:
    while True:
        try:
            batch = it.next()
        except StopIteration:
            break
        sel = np.asarray(batch.index, np.int64)
        real = sel[:len(sel) - batch.pad]
        consumed.append([epoch, [int(i) for i in real]])
        xb, yb = X[real], y[real]
        g = xb.T @ (xb @ w - yb) * (LR / (B * active))
        kv.push("wa", nd.array(g[:2].astype(np.float32)))
        kv.push("wb", nd.array(g[2:].astype(np.float32)))
        accepted += 1
        if die_round and rank == die_rank and accepted >= die_round:
            os._exit(0)   # crash: no barrier, no stop, heartbeats cease
        kv.barrier()
        view = kv.membership()
        if view["mep"] != mep:
            mep = view["mep"]
            members = sorted(view["members"])
            if rank not in members:
                print("RETIRED", rank, json.dumps(
                    {"epoch": epoch, "consumed": consumed,
                     "accepted": accepted}), flush=True)
                retired = True
                break
            pos, active = members.index(rank), len(members)
            it.repartition(pos, active)
        if rank == min(members):
            kv.put_job_meta({"round": kv._barrier_round, "epoch": epoch,
                             "mep": mep, "members": members,
                             "data": it.state_dict()})
        kv.pull("wa", out=out_a)
        kv.pull("wb", out=out_b)
        w = np.concatenate([out_a.asnumpy(),
                            out_b.asnumpy()]).astype(np.float64)
        if round_sleep:
            time.sleep(round_sleep)
    epoch += 1
    if epoch < EPOCHS and not retired:
        it.reset()

if not retired:
    mse = float(np.mean((X @ w - y) ** 2))
    print("RESULT", rank, json.dumps(
        {"consumed": consumed, "final_w": [float(v) for v in w],
         "mse": mse, "accepted": accepted}), flush=True)
    kv.barrier()
    if rank == 0:
        stats = [kv.server_stats(server=s) for s in
                 range(int(os.environ.get("DMLC_NUM_SERVER", "1")))]
        print("STATS", json.dumps(stats), flush=True)
    kv.barrier()
    if rank == 0:
        kv.stop_server()
'''

def _spec(d):
    return ",".join("%s=%d" % (k, v) for k, v in sorted(d.items()))


def _spawn_server(env, sid, server_chaos):
    senv = dict(env, DMLC_ROLE="server", DMLC_SERVER_ID=str(sid),
                # suppress the package's server re-exec bootstrap: this
                # wrapper must regain control after run_server returns
                # to report which injections actually fired
                _MXTPU_SERVER_BOOT="1")
    if server_chaos:
        senv["MXNET_CHAOS"] = _spec(server_chaos)
    return subprocess.Popen([PY, "-c", SERVER], env=senv,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)


def run_class(name, **kw):
    """One 2x2 dist_sync job under a fault class; returns the number
    of injections observed fired across all processes.  Never leaks
    children: a failed assertion kills every spawned process so later
    classes' ports stay free."""
    procs = []
    try:
        return _run_class(name, procs, **kw)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _run_class(name, procs, worker_chaos=None, server_chaos=None,
               worker_env=None, server_env=None, die_after=0,
               kill_server0=False, port=9610):
    snapdir = tempfile.mkdtemp(prefix="netchaos_%s_" % name)
    env = dict(os.environ)
    env.pop("MXNET_CHAOS", None)
    env.update({
        "NC_REPO": REPO,
        "NC_STEPS": str(STEPS),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(N_WORKERS),
        "DMLC_NUM_SERVER": str(N_SERVERS),
        "MXNET_KVSTORE_BIGARRAY_BOUND": str(BIG_BOUND),
        "MXNET_KVSTORE_SNAPSHOT_PREFIX": os.path.join(snapdir, "snap"),
        "MXNET_KVSTORE_SNAPSHOT_EVERY": "1",
        "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "0.2",
        "JAX_PLATFORMS": "cpu",
    })
    env.update(server_env or {})
    servers = []
    for sid in range(N_SERVERS):
        chaos_for = dict(server_chaos or {})
        if kill_server0 and sid == 0:
            # the kill switch arms ONLY server 0's first incarnation
            chaos_for["net_kill_server_at"] = 3
        servers.append(_spawn_server(env, sid, chaos_for))
    procs.extend(servers)
    wenv_base = dict(env)
    wenv_base.update(worker_env or {})
    wenv_base.setdefault("MXNET_KVSTORE_RPC_TIMEOUT", "4")
    wenv_base.setdefault("MXNET_KVSTORE_RPC_RETRIES", "8")
    if worker_chaos:
        wenv_base["MXNET_CHAOS"] = _spec(worker_chaos)
    workers = []
    for rank in range(N_WORKERS):
        wenv = dict(wenv_base, DMLC_ROLE="worker",
                    DMLC_WORKER_RANK=str(rank))
        if die_after:
            wenv["NC_DIE_AFTER_STEP"] = str(die_after)
        workers.append(subprocess.Popen([PY, "-c", WORKER], env=wenv,
                                        stdout=subprocess.PIPE,
                                        stderr=subprocess.PIPE))
    procs.extend(workers)
    fired = 0
    if kill_server0:
        # wait for the injected hard kill, then restart the server on
        # the same port + snapshot prefix WITHOUT the kill switch
        deadline = time.time() + 90
        while servers[0].poll() is None and time.time() < deadline:
            time.sleep(0.1)
        rc = servers[0].poll()
        assert rc == 137, \
            "server 0 should have been hard-killed, rc=%r" % (rc,)
        fired += 1
        servers[0] = _spawn_server(env, 0, server_chaos or {})
        procs.append(servers[0])
        print("  server 0 killed (rc=137) and restarted", flush=True)

    outs = []
    for w in workers:
        stdout, stderr = w.communicate(timeout=180)
        assert w.returncode == 0, \
            "[%s] worker failed:\n%s" % (name, stderr.decode()[-3000:])
        outs.append(stdout.decode())

    # -- value assertions: convergence-equivalent pulls ------------------
    # sync + no updater => pulled value = the round's aggregated sum
    both = float(sum(r + 1 for r in range(N_WORKERS)))     # 3.0
    for out in outs:
        lines = out.splitlines()
        res = [l for l in lines if l.startswith("RESULT")]
        if not res:
            assert die_after, "[%s] missing RESULT:\n%s" % (name, out)
            continue            # the deliberately-dead worker
        rank = int(res[0].split(" ", 2)[1])
        vals = json.loads(res[0].split(" ", 2)[2])
        for step, (w_val, b_val) in enumerate(vals, 1):
            if die_after and step > die_after:
                want = 1.0      # only worker 0 contributes post-evict
            else:
                want = both
            assert w_val == want and b_val == want, \
                "[%s] rank %d step %d: got (%s, %s), want %s" \
                % (name, rank, step, w_val, b_val, want)
        for l in lines:
            if l.startswith("CHAOSFIRED"):
                fired += sum(json.loads(l.split(" ", 2)[2]).values())

    # -- exactly-once: server apply counters match logical rounds --------
    stats_line = [l for o in outs for l in o.splitlines()
                  if l.startswith("STATS")]
    assert stats_line, "[%s] rank 0 printed no STATS" % name
    stats = json.loads(stats_line[0].split(" ", 1)[1])
    for st in stats:
        nkeys = len(st["keys"])
        assert nkeys >= 1, "[%s] server %s lost every key: %s" \
            % (name, st["server_id"], st)
        assert st["applies"] == STEPS * nkeys, \
            "[%s] server %s: applies=%d != steps*keys=%d (%s) — " \
            "retry/duplicate was NOT exactly-once" \
            % (name, st["server_id"], st["applies"], STEPS * nkeys, st)
        if die_after:
            assert 1 in st["evicted"], \
                "[%s] server %s never evicted dead rank 1: %s" \
                % (name, st["server_id"], st)
    if kill_server0:
        assert stats[0]["snapshots"] >= 1, \
            "[%s] restarted server 0 never snapshotted: %s" \
            % (name, stats[0])

    for i, s in enumerate(servers):
        try:
            sout, serr = s.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            s.kill()
            raise AssertionError("[%s] server %d did not stop" % (name, i))
        assert s.returncode == 0, \
            "[%s] server %d rc=%d:\n%s" % (name, i, s.returncode,
                                           serr.decode()[-2000:])
        for l in sout.decode().splitlines():
            if l.startswith("CHAOSFIRED"):
                fired += sum(json.loads(l.split(" ", 1)[1]).values())
    if die_after:
        fired += 1              # the real worker death is the fault
    return fired


# ---------------------------------------------------------------------------
# Elastic driver
# ---------------------------------------------------------------------------

N_SAMPLES = 48          # must match ELASTIC_WORKER's N
ELASTIC_SERVERS = 2


def _server0_keys():
    """How many of the two param keys the crc32 shard map puts on
    server 0 (the server the driver polls for round progress)."""
    import zlib
    return sum(1 for k in ("wa", "wb")
               if zlib.crc32(k.encode()) % ELASTIC_SERVERS == 0)


def _elastic_stats(port, server=0):
    import socket
    from mxnet_tpu._kvstore_impl import _rpc_call, _MSG_CMD
    s = socket.create_connection(("127.0.0.1", port + server),
                                 timeout=10)
    try:
        return _rpc_call(s, _MSG_CMD, {"head": "stats"})[0]
    finally:
        s.close()


def _wait_stats(port, cond, what, deadline_s=120):
    """Poll server 0's stats until *cond(stats)* holds — the drill's
    'under load' trigger points are expressed in observable training/
    membership progress, not wall-clock guesses (a joiner's python+jax
    import alone can take seconds under CI load)."""
    deadline = time.time() + deadline_s
    while True:
        try:
            st = _elastic_stats(port)
            if cond(st):
                return st
        except (ConnectionError, OSError):
            pass
        assert time.time() < deadline, "timed out waiting for " + what
        time.sleep(0.1)


def _wait_rounds(port, rounds, deadline_s=120):
    per_round = max(1, _server0_keys())
    return _wait_stats(
        port, lambda st: st["applies"] >= rounds * per_round,
        "%d completed rounds" % rounds, deadline_s)


def _spawn_elastic_worker(env, rank, joiner=False, die_after=0):
    wenv = dict(env, DMLC_ROLE="worker", DMLC_WORKER_RANK=str(rank))
    if joiner:
        wenv["EW_JOINER"] = "1"
    if die_after:
        wenv["EW_DIE_RANK"] = str(rank)
        wenv["EW_DIE_AFTER_ROUND"] = str(die_after)
    return subprocess.Popen([PY, "-c", ELASTIC_WORKER], env=wenv,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)


def run_elastic(name, port, init_world, ops=(), die=None,
                expect_cover=True, epochs=3):
    procs = []
    try:
        return _run_elastic(name, procs, port, init_world, ops, die,
                            expect_cover, epochs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _run_elastic(name, procs, port, init_world, ops, die,
                 expect_cover, epochs):
    """One elastic scenario.  *ops* is a timeline of
    ``(after_rounds, action, arg)`` with action in:
      'resize'  — operator_resize(arg) against the live job,
      'spawn'   — start a joiner worker with rank *arg*.
    *die* = (rank, after_its_round_k): that worker hard-exits with no
    ceremony (eviction path).  Returns the scenario's summary dict."""
    from mxnet_tpu.resilience.elastic import operator_resize
    env = dict(os.environ)
    env.pop("MXNET_CHAOS", None)
    env.update({
        "NC_REPO": REPO,
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(init_world),
        "DMLC_NUM_SERVER": str(ELASTIC_SERVERS),
        "MXNET_KVSTORE_HEARTBEAT_INTERVAL": "0.2",
        "MXNET_KVSTORE_SYNC_TIMEOUT": "4",
        "MXNET_KVSTORE_EVICT_TIMEOUT": "1.0",
        "MXNET_KVSTORE_RPC_TIMEOUT": "30",
        "MXNET_KVSTORE_RPC_RETRIES": "4",
        "MXNET_KVSTORE_JOIN_TIMEOUT": "90",
        "MXNET_KVSTORE_ADMIT_POLL": "0.1",
        "EW_EPOCHS": str(epochs),
        "EW_ROUND_SLEEP": "0.12",
        "JAX_PLATFORMS": "cpu",
    })
    env.pop("MXNET_KVSTORE_SNAPSHOT_PREFIX", None)
    servers = [_spawn_server(env, sid, {})
               for sid in range(ELASTIC_SERVERS)]
    procs.extend(servers)
    workers = []          # [(rank, proc)] — a retired rank's process
    for rank in range(init_world):   # and its later replacement both
        d = die[1] if die and die[0] == rank else 0   # get collected
        workers.append((rank, _spawn_elastic_worker(env, rank,
                                                    die_after=d)))
    procs.extend(p for _, p in workers)

    resizes = joins_spawned = 0
    for after_rounds, action, arg in ops:
        if after_rounds:
            _wait_rounds(port, after_rounds)
        if action == "resize":
            operator_resize(arg, host="127.0.0.1", root_port=port,
                            num_servers=ELASTIC_SERVERS)
            resizes += 1
            print("  [%s] resize -> %d after >=%d rounds"
                  % (name, arg, after_rounds), flush=True)
        elif action == "spawn":
            joiner = _spawn_elastic_worker(env, arg, joiner=True)
            workers.append((arg, joiner))
            procs.append(joiner)
            joins_spawned += 1
            print("  [%s] joiner rank %d spawned after >=%d rounds"
                  % (name, arg, after_rounds), flush=True)
        elif action == "await_members":
            # gate the timeline on the applied transition, so e.g. a
            # grow only fires once the shrink retired the old rank,
            # and the drill only proceeds once joiners are admitted
            _wait_stats(port,
                        lambda st: st["members"] == sorted(arg),
                        "%s membership %s" % (name, sorted(arg)))
            print("  [%s] membership now %s" % (name, sorted(arg)),
                  flush=True)
        elif action == "await_pending":
            # the joiners' heartbeats prove their processes finished
            # importing — only then is commanding the grow meaningful
            _wait_stats(
                port,
                lambda st: set(arg) <= set(st["pending_join"])
                | set(st["members"]),
                "%s ranks %s announcing themselves" % (name,
                                                       sorted(arg)))
            print("  [%s] ranks %s announced" % (name, sorted(arg)),
                  flush=True)

    results, retireds, joined, stats = {}, [], {}, None
    for rank, w in workers:
        stdout, stderr = w.communicate(timeout=240)
        assert w.returncode == 0, \
            "[%s] worker %d rc=%r:\n%s" % (name, rank, w.returncode,
                                           stderr.decode()[-3000:])
        for line in stdout.decode().splitlines():
            tag, _, rest = line.partition(" ")
            if tag == "RESULT":
                results[rank] = json.loads(rest.split(" ", 1)[1])
            elif tag == "RETIRED":
                retireds.append((rank, json.loads(rest.split(" ", 1)[1])))
            elif tag == "JOINED":
                joined[rank] = json.loads(rest.split(" ", 1)[1])
            elif tag == "STATS":
                stats = json.loads(rest)
    victim = die[0] if die else None

    # -- exactly-once sample coverage per epoch --------------------------
    if expect_cover:
        per_epoch = {}
        for blob in list(results.values()) + [b for _, b in retireds]:
            for epoch, idxs in blob["consumed"]:
                per_epoch.setdefault(epoch, []).extend(idxs)
        for epoch in range(epochs):
            counts = {}
            for i in per_epoch.get(epoch, ()):
                counts[i] = counts.get(i, 0) + 1
            missing = [i for i in range(N_SAMPLES) if i not in counts]
            dupes = {i: c for i, c in counts.items() if c != 1}
            assert not missing and not dupes, \
                "[%s] epoch %d coverage not exactly-once: missing=%s " \
                "dupes=%s" % (name, epoch, missing[:10],
                              dict(list(dupes.items())[:10]))

    # -- all completing workers pulled the SAME final weights ------------
    finals = {r: tuple(b["final_w"]) for r, b in results.items()}
    assert len(set(finals.values())) == 1, \
        "[%s] divergent final weights: %s" % (name, finals)

    # -- zero lost accepted pushes: applies == rounds x keys -------------
    assert stats is not None, "[%s] rank 0 printed no STATS" % name
    rounds = results[0]["accepted"]
    for st in stats:
        nkeys = len(st["keys"])
        assert st["applies"] == rounds * nkeys, \
            "[%s] server %s: applies=%d != rounds(%d) * keys(%d) — " \
            "an accepted push was lost or double-applied (%s)" \
            % (name, st["server_id"], st["applies"], rounds, nkeys, st)

    mse = results[0]["mse"]
    summary = {"resizes": resizes, "joins": len(joined),
               "retired": sorted(r for r, _ in retireds), "mse": mse,
               "rounds": rounds, "mep": stats[0].get("mep"),
               "members": stats[0].get("members"),
               "evictions": 1 if victim is not None else 0,
               "evicted": stats[0].get("evicted")}
    assert len(joined) == joins_spawned, \
        "[%s] %d joiners spawned but %d admitted" \
        % (name, joins_spawned, len(joined))
    if victim is not None:
        assert victim in stats[0].get("evicted", ()) or \
            victim in stats[0].get("members", ()), \
            "[%s] victim %d neither evicted nor re-admitted: %s" \
            % (name, victim, stats[0])
    return summary


def main():
    classes = [
        ("baseline", {}),
        ("worker_faults", dict(
            worker_chaos={"net_partition": 2, "net_dup_request": 2,
                          "net_delay_request": 2, "net_delay_ms": 100})),
        ("drop_reply", dict(
            server_chaos={"net_drop_reply": 2},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("delay_reply", dict(
            # delay > RPC timeout: the worker must ride the full
            # timeout -> reconnect -> retry -> dedup path
            server_chaos={"net_delay_reply": 1, "net_delay_ms": 3500},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("torn", dict(
            worker_chaos={"net_torn_request": 2},
            server_chaos={"net_torn_reply": 1},
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "2"})),
        ("server_kill", dict(kill_server0=True)),
        ("eviction", dict(
            die_after=1,
            worker_env={"MXNET_KVSTORE_RPC_TIMEOUT": "10"},
            server_env={"MXNET_KVSTORE_SYNC_TIMEOUT": "3",
                        "MXNET_KVSTORE_EVICT_TIMEOUT": "1.0"})),
    ]
    total_fired = 0
    recovered = 0
    for i, (name, kw) in enumerate(classes):
        t0 = time.time()
        print("== netchaos class: %s ==" % name, flush=True)
        fired = run_class(name, port=9610 + 10 * i, **kw)
        if name != "baseline":
            assert fired > 0, \
                "[%s] armed faults never fired — the drill is inert" \
                % name
            recovered += 1
        total_fired += fired
        print("  ok (%d injections, %.1fs)" % (fired, time.time() - t0),
              flush=True)

    # -- elastic scenarios (grow/shrink/resize under load) ---------------
    scenarios = [
        # fixed-size reference run: its MSE is the convergence-
        # equivalence yardstick for every elastic run
        ("elastic_baseline3", dict(init_world=3)),
        # operator shrink 3->2 under load: rank 2 retires cleanly,
        # survivors re-shard the remaining epoch
        ("elastic_shrink", dict(init_world=3,
                                ops=[(4, "resize", 2)])),
        # operator grow 2->3 under load: the joiner is admitted at a
        # round boundary and takes over its shard mid-epoch.  Spawn
        # first, command the grow once its heartbeats prove it is up
        # (imports take seconds under CI load), then gate on the
        # admission actually landing
        ("elastic_grow", dict(init_world=2,
                              ops=[(1, "spawn", 2),
                                   (0, "await_pending", [2]),
                                   (0, "resize", 3),
                                   (0, "await_members", [0, 1, 2])],
                              epochs=4)),
        # a worker dies without ceremony (evicted; its in-flight
        # batch is lost, so coverage is not exactly-once) and a
        # REPLACEMENT with the same rank rejoins mid-epoch
        ("elastic_evict_replace", dict(init_world=3, die=(2, 4),
                                       ops=[(7, "spawn", 2),
                                            (0, "await_members",
                                             [0, 1, 2])],
                                       expect_cover=False, epochs=5)),
        # the acceptance gate: operator-commanded 3 -> 2 -> 4 chain
        # under load, exactly-once coverage throughout
        ("elastic_resize_chain", dict(init_world=3,
                                      ops=[(4, "resize", 2),
                                           (0, "await_members",
                                            [0, 1]),
                                           (0, "spawn", 2),
                                           (0, "spawn", 3),
                                           (0, "await_pending",
                                            [2, 3]),
                                           (0, "resize", 4),
                                           (0, "await_members",
                                            [0, 1, 2, 3])],
                                      epochs=6)),
    ]
    totals = {"resizes": 0, "joins": 0, "evictions": 0}
    baseline_mse = None
    for i, (name, kw) in enumerate(scenarios):
        t0 = time.time()
        print("== elastic scenario: %s ==" % name, flush=True)
        summary = run_elastic(name, port=9710 + 20 * i, **kw)
        if name == "elastic_baseline3":
            baseline_mse = summary["mse"]
        else:
            # convergence equivalence: same data, same epochs — the
            # elastic trajectory differs (round grouping changes with
            # the world size) but must land in the same basin
            assert summary["mse"] < max(5e-3, 4.0 * baseline_mse), \
                "[%s] final mse %.5f vs baseline %.5f — elastic run " \
                "did not converge equivalently" \
                % (name, summary["mse"], baseline_mse)
        totals["resizes"] += summary["resizes"]
        totals["joins"] += summary["joins"]
        totals["evictions"] += summary["evictions"]
        print("  ok (%s, %.1fs)" % (
            ", ".join("%s=%s" % kv for kv in sorted(summary.items())),
            time.time() - t0), flush=True)

    print("elastic: resizes=%d joins=%d evictions=%d ok"
          % (totals["resizes"], totals["joins"], totals["evictions"]),
          flush=True)
    print("netchaos: faults=%d recovered=%d ok"
          % (total_fired, recovered), flush=True)


if __name__ == "__main__":
    main()
