"""Serving-subsystem CI smoke (ci/run_tests.sh stage).

A two-model registry serving concurrent mixed-size traffic through
the dynamic batcher, with the graftsan sanitizers on (the stage
exports MXNET_SAN=all) and serve events recorded.  Fails on:

* any compile after warmup — the request path must dispatch only
  AOT programs (``compile_count`` pinned at one per bucket, and the
  underlying jit's trace cache pinned at ZERO);
* a wrong answer — every future's rows are checked bit-exact against
  the eager single-shot forward of the same model;
* any graftsan report (the batcher's locks/queues/threads all come
  from the sanitizer factories — a race or lock-order cycle in the
  dispatcher shows up here, in seconds);
* missing latency accounting (p50/p99 come out of the
  ``serve_request_seconds`` histogram).

Last stdout line is the scrapeable summary::

    serve: reqs=N batches=M compiles=K ok
"""

import os
import sys
import tempfile
import threading

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "serve")
os.environ.setdefault(
    "MXNET_OBS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="serve_smoke_"),
                 "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import serve, sym  # noqa: E402
from mxnet_tpu.observability import events as obs_events  # noqa: E402
from mxnet_tpu.observability import metrics as obs_metrics  # noqa: E402
import tools.graftsan as graftsan  # noqa: E402

THREADS = 6
REQS_PER_THREAD = 25
BUCKETS = (1, 2, 4, 8)


def build_model(dim, hidden, classes, seed):
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="h")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="o")
    net = sym.softmax(net)
    rs = np.random.RandomState(seed)
    arg_shapes, _, _ = net.infer_shape(data=(1, dim))
    params = {n: mx.nd.array(rs.randn(*s).astype(np.float32) * 0.1)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}
    return net, params


class EagerRungRefs:
    """Bit-exact references for a request under dynamic batching.

    A coalesced request's rows run at whatever rung the batch landed
    on, so the exact baseline is 'the same rows, zero-padded, through
    the EAGER executor at rung B' for each rung B >= rows — serving
    must reproduce one of those bit-for-bit (anything else means
    coalescing/padding/splitting corrupted the rows).  One eager
    executor per rung, reused across requests (tests/test_serve.py
    separately proves natural-batch bit-equality and pad-invariance)."""

    def __init__(self, net, params, dim):
        self._net = net
        self._params = params
        self._dim = dim
        self._execs = {}

    def _exec_at(self, b):
        ex = self._execs.get(b)
        if ex is None:
            args = dict(self._params)
            args["data"] = mx.nd.array(np.zeros((b, self._dim),
                                                np.float32))
            ex = self._net.bind(mx.cpu(), args)
            self._execs[b] = ex
        return ex

    def refs(self, x):
        rows = x.shape[0]
        out = []
        for b in BUCKETS:
            if b < rows:
                continue
            buf = np.zeros((b, self._dim), np.float32)
            buf[:rows] = x
            ex = self._exec_at(b)
            out.append(ex.forward(data=mx.nd.array(buf))[0]
                       .asnumpy()[:rows])
        return out


def hist_quantile(snap, q):
    """Upper-bound estimate of quantile *q* from a histogram
    snapshot (cumulative Prometheus buckets)."""
    total = snap["count"]
    if not total:
        return None
    target = q * total
    for le, cum in snap["buckets"].items():
        if le != "+Inf" and cum >= target:
            return float(le)
    return float("inf")


def main():
    failures = []
    models = {}
    registry = serve.ModelRegistry()
    ladder = serve.BucketLadder(batches=BUCKETS)
    # fixed integer seeds: hash(name) varies per interpreter
    # (PYTHONHASHSEED), which would make a bit-equality failure
    # unreproducible across runs
    for name, dims, seed in (("alpha", (12, 32, 4), 11),
                             ("beta", (7, 16, 3), 23)):
        net, params = build_model(*dims, seed=seed)
        pred = registry.load(name, net, params,
                             data_shapes={"data": (1, dims[0])},
                             ladder=ladder)
        if pred.compile_count != len(BUCKETS):
            failures.append(
                "%s: warm built %d programs for %d buckets"
                % (name, pred.compile_count, len(BUCKETS)))
        models[name] = (net, params, pred, dims[0])
    registry.alias("stable", "alpha")

    # deterministic request schedule; per-rung eager references
    # computed SERIALLY before any traffic flows
    rs = np.random.RandomState(7)
    pools, rung_refs = {}, {}
    for name, (net, params, _, dim) in models.items():
        pools[name] = rs.randn(32, dim).astype(np.float32)
        rung_refs[name] = EagerRungRefs(net, params, dim)
    schedule = {}
    for tid in range(THREADS):
        rw = np.random.RandomState(tid)
        plan = []
        for i in range(REQS_PER_THREAD):
            name = ("alpha", "beta", "stable")[(tid + i) % 3]
            resolved = "alpha" if name == "stable" else name
            rows = int(rw.randint(1, 5))
            lo = int(rw.randint(0, 32 - rows))
            x = pools[resolved][lo:lo + rows]
            plan.append((name, x, rung_refs[resolved].refs(x)))
        schedule[tid] = plan

    warm_compiles = {n: m[2].compile_count for n, m in models.items()}
    errors = []

    def worker(tid):
        for i, (name, x, refs) in enumerate(schedule[tid]):
            fut = registry.submit(name, x)
            out = fut.result(60)[0]
            if out.shape != refs[0].shape:
                errors.append("%s: got shape %s for %s" %
                              (name, out.shape, refs[0].shape))
            elif not any(np.array_equal(out, r) for r in refs):
                errors.append(
                    "%s req %d/%d: rows are not bit-equal to the "
                    "eager forward at ANY rung — coalescing/padding "
                    "corrupted them" % (name, tid, i))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    failures.extend(errors[:5])

    total_reqs = sum(registry.batcher(n).request_count for n in models)
    total_batches = sum(registry.batcher(n).batch_count for n in models)
    total_compiles = sum(m[2].compile_count for m in models.values())
    expect_reqs = THREADS * REQS_PER_THREAD
    if total_reqs != expect_reqs:
        failures.append("request accounting: %d submitted, %d counted"
                        % (expect_reqs, total_reqs))
    if total_batches >= total_reqs:
        failures.append(
            "dynamic batching inert: %d batches for %d requests "
            "(no coalescing happened)" % (total_batches, total_reqs))
    for name, (_, _, pred, _) in models.items():
        if pred.compile_count != warm_compiles[name]:
            failures.append(
                "%s: %d compiles happened in the REQUEST PATH"
                % (name, pred.compile_count - warm_compiles[name]))
        if pred.jit_cache_size() != 0:
            failures.append(
                "%s: jit trace cache is %d (something traced instead "
                "of dispatching an AOT program)"
                % (name, pred.jit_cache_size()))

    # latency accounting: p50/p99 out of the request histogram
    snap = obs_metrics.snapshot().get("serve_request_seconds")
    if not snap or snap["count"] < expect_reqs:
        failures.append("serve_request_seconds histogram missing or "
                        "short: %r" % (snap,))
        p50 = p99 = None
    else:
        p50 = hist_quantile(snap, 0.50)
        p99 = hist_quantile(snap, 0.99)
        print("serve smoke: p50<=%.4fs p99<=%.4fs (n=%d)"
              % (p50, p99, snap["count"]))

    # serve events recorded (load + one compile event per program)
    try:
        evs = [e for e in obs_events.read_events() if e["ev"] == "serve"]
    except OSError:
        evs = []
    loads = [e for e in evs if e.get("kind") == "load"]
    compiles = [e for e in evs if e.get("kind") == "compile"]
    if len(loads) < 2 or len(compiles) < total_compiles:
        failures.append(
            "serve events incomplete: %d loads, %d compile events for "
            "%d programs" % (len(loads), len(compiles), total_compiles))

    # registry lifecycle under traffic already done; unload must close
    registry.unload("beta")
    if "beta" in registry.names():
        failures.append("unload left beta resident")

    reports = graftsan.reports()
    failures.extend(graftsan.format_report(r) for r in reports)

    if failures:
        print("\n".join(failures), file=sys.stderr)
        print("serve smoke: FAIL", file=sys.stderr)
        print("serve: reqs=%d batches=%d compiles=%d FAIL"
              % (total_reqs, total_batches, total_compiles))
        return 1
    print("serve: reqs=%d batches=%d compiles=%d ok"
          % (total_reqs, total_batches, total_compiles))
    return 0


if __name__ == "__main__":
    sys.exit(main())
