"""Observability CI smoke (ci/run_tests.sh stage).

A short fused-step training run with ``MXNET_OBS=all``, asserting the
telemetry contract end to end:

* the expected instruments exist in the metrics registry with sane
  values (fused dispatches == steps, latency histogram count == steps,
  host transfers observed, exposition text parses),
* ``events.jsonl`` exists, every line is well-formed JSON with the
  required envelope (ts/ev/pid/seq), seq is gapless, and the run's
  compile event is present,
* ``profiler.dump()`` carries the registry instruments as chrome-trace
  Counter events next to the spans.

Seconds, CPU-only.  The last stdout line is the scrapeable summary
(``obs: instruments=N events=M ok``), mirroring the graftlint and
graftsan stages.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_OBS", "all")
_tmpdir = tempfile.mkdtemp(prefix="obs_smoke_")
os.environ.setdefault("MXNET_OBS_PATH",
                      os.path.join(_tmpdir, "events.jsonl"))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import sym, profiler  # noqa: E402
from mxnet_tpu.io import DataBatch  # noqa: E402
from mxnet_tpu.observability import events, metrics  # noqa: E402

STEPS = 8


def build_module():
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, label, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([("data", (16, 8))], [("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def main():
    failures = []
    rng = np.random.RandomState(0)
    batch = DataBatch(
        data=[mx.nd.array(rng.randn(16, 8).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (16,)).astype(np.float32))])

    profiler.reset_counters()
    mod = build_module()
    for _ in range(STEPS):
        mod.forward_backward_update(batch)
    mod.get_outputs()[0].asnumpy()

    # -- instruments ---------------------------------------------------
    snap = metrics.snapshot()
    expected = {
        "fused_step_dispatches": lambda s: s["value"] == STEPS,
        "fused_step_compiles": lambda s: s["value"] == 1,
        "fused_step_dispatch_seconds": lambda s: s["count"] == STEPS,
        "host_transfers_total": lambda s: s["value"] >= 1,
        "host_transfer_bytes_total": lambda s: s["value"] >= 1,
        "obs_events_total": lambda s: s["value"] >= 1,
    }
    for name, check in expected.items():
        if name not in snap:
            failures.append("instrument %r missing from the registry "
                            "(have: %s)" % (name, sorted(snap)))
        elif not check(snap[name]):
            failures.append("instrument %r has unexpected value: %r"
                            % (name, snap[name]))

    # the serving fault-tolerance instruments register on import and
    # must be in the catalog (values are exercised by
    # ci/serve_chaos_drill.py; here the contract is presence — a
    # scraper provisioning dashboards sees them from process start)
    import mxnet_tpu.serve  # noqa: F401
    snap = metrics.snapshot()
    for name in ("serve_requests_shed_total",
                 "serve_requests_expired_total",
                 "serve_requests_cancelled_total",
                 "serve_dispatcher_restarts_total",
                 "serve_drains_total",
                 "serve_batcher_dirty_closes_total",
                 "serve_queue_age_seconds"):
        if name not in snap:
            failures.append("serve instrument %r missing from the "
                            "registry catalog" % name)

    # quantization instruments register on import (serve registry +
    # quantize package) and the quantize event category must be known
    # — values are exercised by ci/quant_smoke.py, the contract here
    # is catalog presence (docs/quantization.md)
    import mxnet_tpu.quantize  # noqa: F401
    snap = metrics.snapshot()
    for name in ("serve_quantized_models",
                 "quant_calibration_batches_total",
                 "quant_accuracy_gate_failures_total"):
        if name not in snap:
            failures.append("quantization instrument %r missing from "
                            "the registry catalog" % name)
    if "quantize" not in events._CATEGORIES:
        failures.append("'quantize' is not a known event category")

    # graftsched registers its explorer counters on import and emits
    # under the "sched" category (docs/sanitizers.md "Schedule
    # exploration"); values are exercised by ci/sched_drill.py, the
    # contract here is catalog presence
    import tools.graftsched  # noqa: F401
    snap = metrics.snapshot()
    for name in ("graftsched_schedules_total",
                 "graftsched_findings_total"):
        if name not in snap:
            failures.append("graftsched instrument %r missing from "
                            "the registry catalog" % name)
    if "sched" not in events._CATEGORIES:
        failures.append("'sched' is not a known event category")

    # exposition must render and carry the fused-step counter
    expo = metrics.exposition()
    if "mxnet_fused_step_dispatches %d" % STEPS not in expo:
        failures.append("exposition text lacks the fused-step counter")

    # -- device-prefetched input path ----------------------------------
    # a short prefetched epoch exercises the input-pipeline telemetry
    # (docs/perf_input_pipeline.md): one wait observation per consumed
    # batch, the stall counter + ring-occupancy gauge live, and the
    # step loop's elided device_puts counted.  Runs AFTER the exact
    # fused-step count assertions above (these are extra steps).
    from mxnet_tpu.io import DevicePrefetcher, NDArrayIter
    pf = DevicePrefetcher(
        NDArrayIter(rng.randn(64, 8).astype(np.float32),
                    rng.randint(0, 4, 64).astype(np.float32),
                    batch_size=16, last_batch_handle="discard"),
        depth=2)
    try:
        pf_steps = 0
        for b in pf:
            mod.forward_backward_update(b)
            pf_steps += 1
        mod.get_outputs()[0].asnumpy()
    finally:
        pf.close()
    snap = metrics.snapshot()
    input_expected = {
        "input_wait_seconds": lambda s: s["count"] >= pf_steps,
        "steps_input_stalled_total": lambda s: s["value"] >= 0,
        "device_prefetch_ring_occupancy": lambda s: True,
        "device_put_elided_total":
            lambda s: s["value"] >= 2 * pf_steps,
    }
    for name, check in input_expected.items():
        if name not in snap:
            failures.append("input instrument %r missing from the "
                            "registry (have: %s)" % (name, sorted(snap)))
        elif not check(snap[name]):
            failures.append("input instrument %r has unexpected value: "
                            "%r" % (name, snap[name]))

    # -- continuous-batching decode telemetry --------------------------
    # a tiny paged-decode workout: the pool gauges must track block
    # ownership, the decode counters/histogram must record the ticks
    # and tokens, and the 'decode' event kinds must land in
    # events.jsonl (docs/observability.md; ci/decode_smoke.py runs
    # the full drill — here the contract is the telemetry)
    import warnings as _warnings
    from mxnet_tpu.serve.decode import DecodeEngine
    from mxnet_tpu.test_utils import tiny_attention_lm
    dp, dstep, dprefill, dtok_spec, din_spec = tiny_attention_lm(
        vocab=16, dim=8, seed=3)
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")     # CPU XLA ignores donation
        deng = DecodeEngine(dstep, dprefill, dtok_spec, din_spec,
                            params=dp, max_len=8, block_size=4,
                            num_blocks=6, session_rungs=(1, 2),
                            donate=True, label="obs-smoke")
        dsess = deng.admit({"tok": np.asarray([1, 2, 3], np.int32)},
                           max_new_tokens=3)
        deng.prefill(dsess)
        snap = metrics.snapshot()
        if snap.get("serve_kv_blocks_in_use", {}).get("value") != 1:
            failures.append("serve_kv_blocks_in_use should read 1 "
                            "after a 3-token admission, got %r"
                            % (snap.get("serve_kv_blocks_in_use"),))
        if snap.get("serve_decode_active_sessions",
                    {}).get("value") != 1:
            failures.append("serve_decode_active_sessions should "
                            "read 1, got %r"
                            % (snap.get("serve_decode_active_sessions"),))
        while not dsess.done():
            deng.tick([dsess])
        deng.close()
    snap = metrics.snapshot()
    decode_expected = {
        "serve_decode_steps_total": lambda s: s["value"] >= 3,
        "serve_decode_tokens_total": lambda s: s["value"] >= 3,
        "serve_decode_token_seconds": lambda s: s["count"] >= 3,
        "serve_decode_active_sessions": lambda s: s["value"] == 0,
        "serve_kv_blocks_in_use": lambda s: s["value"] == 0,
        "serve_kv_blocks_total": lambda s: s["value"] == 0,
        # the fault-tolerance counters must exist (registered at
        # import) even when this clean workout never trips them
        "serve_decode_failovers_total": lambda s: s["value"] >= 0,
        "serve_decode_rebuilds_total": lambda s: s["value"] >= 0,
        "serve_decode_resumed_sessions_total":
            lambda s: s["value"] >= 0,
    }
    for name, check in decode_expected.items():
        if name not in snap:
            failures.append("decode instrument %r missing from the "
                            "registry (have: %s)"
                            % (name, sorted(snap)))
        elif not check(snap[name]):
            failures.append("decode instrument %r has unexpected "
                            "value: %r" % (name, snap[name]))

    # -- elastic membership telemetry ----------------------------------
    # an in-process server walks join + resize: the active-workers
    # gauge must track the expected-contributor set and the
    # 'membership' event kind must record the transition with old/new
    # epochs (docs/observability.md)
    import socket
    import threading
    from mxnet_tpu._kvstore_impl import (
        KVStoreServer, _rpc_call, _MSG_HEARTBEAT, _MSG_BARRIER,
        _MSG_CMD)
    srv = KVStoreServer(sync_mode=True, num_workers=1)
    st = threading.Thread(target=srv.run, daemon=True)
    st.start()
    conn = socket.create_connection(("127.0.0.1", srv.port), timeout=30)
    try:
        _rpc_call(conn, _MSG_CMD, {"head": "resize", "body": 2,
                                   "req": [0, 1, 1]})
        _rpc_call(conn, _MSG_HEARTBEAT, {"node": "worker1"})
        # the grow + admission apply at the barrier boundary
        _rpc_call(conn, _MSG_BARRIER, {"rank": 0, "round": 1,
                                       "req": [0, 2, 1]})
        stats = _rpc_call(conn, _MSG_CMD, {"head": "stats"})[0]
        if stats.get("members") != [0, 1]:
            failures.append("membership workout: expected members "
                            "[0, 1], got %r" % (stats.get("members"),))
    finally:
        conn.close()
        srv._stop.set()
        try:
            srv.sock.close()
        except OSError:
            pass
        st.join(timeout=10)
    snap = metrics.snapshot()
    if "kvstore_active_workers" not in snap:
        failures.append("kvstore_active_workers gauge missing from the "
                        "registry")
    elif snap["kvstore_active_workers"]["value"] != 2:
        failures.append("kvstore_active_workers should read 2 after "
                        "the grow, got %r"
                        % (snap["kvstore_active_workers"],))

    # -- serving-fleet telemetry ---------------------------------------
    # an in-process fleet workout: one live replica + one dead
    # address behind the router — the predict must fail over (counter
    # + event), the probe loop must set the ready gauge, and the
    # deploy counter must be in the catalog (ci/fleet_chaos_drill.py
    # exercises its value; docs/observability.md)
    import socket as _socket
    from mxnet_tpu import serve as _serve
    from mxnet_tpu import sym as _sym
    fdata = _sym.var("data")
    fnet = _sym.softmax(_sym.FullyConnected(fdata, num_hidden=4,
                                            name="fh"))
    fshapes, _, _ = fnet.infer_shape(data=(1, 6))
    fparams = {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.1)
               for n, s in zip(fnet.list_arguments(), fshapes)
               if n != "data"}
    freg = _serve.ModelRegistry()
    freg.load("fm", fnet, fparams, data_shapes={"data": (1, 6)},
              ladder=_serve.BucketLadder(batches=(1,)))
    frep = _serve.ReplicaServer(freg).start()
    _dead = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    _dead.bind(("127.0.0.1", 0))
    dead_port = _dead.getsockname()[1]
    _dead.close()
    frouter = _serve.Router([("127.0.0.1", dead_port),
                             ("127.0.0.1", frep.port)], probe=False)
    try:
        frouter.predict("fm", rng.randn(1, 6).astype(np.float32))
        frouter.probe_once()
    finally:
        frouter.close()
        frep.stop()
        freg.close()
    snap = metrics.snapshot()
    fleet_expected = {
        "fleet_requests_failed_over_total": lambda s: s["value"] >= 1,
        "fleet_router_requests_total": lambda s: s["value"] >= 1,
        "fleet_replica_requests_total": lambda s: s["value"] >= 1,
        "fleet_replicas_ready": lambda s: s["value"] >= 1,
        "fleet_deploys_total": lambda s: s["value"] >= 0,
        "fleet_requests_hedged_total": lambda s: s["value"] >= 0,
        "fleet_replica_dedup_hits_total": lambda s: s["value"] >= 0,
    }
    for name, check in fleet_expected.items():
        if name not in snap:
            failures.append("fleet instrument %r missing from the "
                            "registry (have: %s)"
                            % (name, sorted(snap)))
        elif not check(snap[name]):
            failures.append("fleet instrument %r has unexpected "
                            "value: %r" % (name, snap[name]))

    # -- autotune telemetry --------------------------------------------
    # a tiny stubbed search through the REAL tune() loop: the trial /
    # prune counters must advance and the 'autotune' event kinds must
    # land in events.jsonl (ci/autotune_smoke.py runs a measured
    # search against real serving machinery — here the contract is
    # the telemetry; docs/autotuning.md)
    from mxnet_tpu.autotune import serve_space, synth_serve_trace, tune
    from mxnet_tpu.autotune.search import serve_objective
    at_trace = synth_serve_trace(rate=40, seconds=0.5, dim=4)

    class _ATStub(object):
        trace = at_trace

        @staticmethod
        def _est(config):
            return (float(config["MXNET_SERVE_MAX_WAIT_MS"])
                    + len(config["ladder"]))

        def measure(self, config, budget_frac=1.0):
            return {"ok": True, "offered_rps": 40.0,
                    "achieved_rps": 40.0, "p99_ms": self._est(config),
                    "request_path_compiles": 0}

        def prior(self, config, budget_frac=1.0):
            return self._est(config)

    at_result = tune(serve_space(), _ATStub(), serve_objective(),
                     model="obs-at", workload="serve", trials=8,
                     neighbor_trials=2, seed=0, prune_ratio=1.2,
                     min_keep=2, device="cpu")
    snap = metrics.snapshot()
    at_expected = {
        "autotune_trials_total":
            lambda s: s["value"] == at_result["trials"],
        "autotune_prune_total":
            lambda s: s["value"] == at_result["pruned"]
            and s["value"] >= 1,
    }
    for name, check in at_expected.items():
        if name not in snap:
            failures.append("autotune instrument %r missing from the "
                            "registry (have: %s)"
                            % (name, sorted(snap)))
        elif not check(snap[name]):
            failures.append("autotune instrument %r has unexpected "
                            "value: %r (result trials=%d pruned=%d)"
                            % (name, snap[name], at_result["trials"],
                               at_result["pruned"]))

    # -- events.jsonl --------------------------------------------------
    ev_path = events.path()
    if not os.path.exists(ev_path):
        failures.append("events.jsonl was not created at %s" % ev_path)
        evs = []
    else:
        try:
            evs = events.read_events(ev_path)
        except ValueError as e:
            failures.append("events.jsonl has a malformed line: %s" % e)
            evs = []
    for i, e in enumerate(evs):
        for k in ("ts", "ev", "pid", "seq"):
            if k not in e:
                failures.append("event %d lacks %r: %r" % (i, k, e))
                break
    seqs = [e.get("seq") for e in evs]
    if seqs != list(range(1, len(evs) + 1)):
        failures.append("event seq is not gapless: %s" % seqs[:20])
    if not any(e.get("ev") == "compile" and e.get("fn") == "fused_step"
               for e in evs):
        failures.append("no compile event for the fused step in %s"
                        % [e.get("ev") for e in evs])
    memb = [e for e in evs if e.get("ev") == "membership"]
    actions = {e.get("action") for e in memb}
    if not {"resize", "join"} <= actions:
        failures.append("membership workout should have recorded "
                        "'resize' and 'join' events, got actions %s"
                        % sorted(actions))
    for e in memb:
        if e.get("action") in ("resize", "join", "rejoin", "evict") \
                and ("old_epoch" not in e or "new_epoch" not in e):
            failures.append("membership event lacks old/new epoch: %r"
                            % (e,))
    decode_kinds = {e.get("kind") for e in evs
                    if e.get("ev") == "decode"}
    if not {"session_start", "session_end", "tick",
            "journal"} <= decode_kinds:
        failures.append("decode workout should have recorded "
                        "session_start/session_end/tick/journal "
                        "events, got kinds %s" % sorted(decode_kinds))
    fleet_kinds = {e.get("kind") for e in evs if e.get("ev") == "fleet"}
    if not {"replica_admit", "failover"} <= fleet_kinds:
        failures.append("fleet workout should have recorded "
                        "replica_admit/failover events, got kinds %s"
                        % sorted(fleet_kinds))
    at_kinds = {e.get("kind") for e in evs
                if e.get("ev") == "autotune"}
    if not {"trial_start", "trial_result", "pruned", "promoted",
            "winner"} <= at_kinds:
        failures.append("autotune workout should have recorded "
                        "trial_start/trial_result/pruned/promoted/"
                        "winner events, got kinds %s"
                        % sorted(at_kinds))

    # -- profiler.dump carries the instruments -------------------------
    trace_path = os.path.join(_tmpdir, "trace.json")
    profiler.set_config(filename=trace_path)
    profiler.set_state("run")
    with profiler.scope("obs-smoke"):
        pass
    profiler.dump()
    with open(trace_path) as f:
        trace = json.load(f)
    names = {e.get("name") for e in trace["traceEvents"]}
    if "metrics/fused_step_dispatches" not in names:
        failures.append("chrome trace lacks the registry Counter "
                        "events (names: %s)" % sorted(names))
    if "obs-smoke" not in names:
        failures.append("chrome trace lost its span events")

    if failures:
        for f_ in failures:
            print("obs smoke FAILURE: %s" % f_, file=sys.stderr)
    print("obs: instruments=%d events=%d %s"
          % (len(snap), len(evs), "FAIL" if failures else "ok"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
