"""graftir CI smoke (ci/run_tests.sh stage).

Lowers the representative AOT program set on CPU avals (nothing
executes beyond the builders' own warmups), then proves the auditor
both PASSES the shipped tree and CATCHES the regressions it exists
for:

* clean pass — rules GI001-GI005 report zero new findings and the
  committed manifest diffs all-ok (any drift here is a real PR
  regression, same as ``python -m tools.graftir --check``);
* seeded 2x cost regression — duplicating the compute ops of one
  program must fail the manifest check naming that program;
* stripped donation — removing the ``tf.aliasing_output`` /
  ``jax.buffer_donor`` entry attrs from the fused step must raise
  GI001 naming the program;
* injected f64 — a smuggled f64 op line must raise GI002 naming the
  program.

The point is meta-level drift protection: a refactor that silently
blinds a rule (regex rot against a new jax pretty-printer, a lost
producer declaration) shows up HERE, in seconds — not as a real
regression sailing through CI three PRs later.

Last stdout line is the scrapeable summary:
``graftir: programs=N findings=0 ok``.
"""

import os
import sys

os.environ.setdefault("MXNET_SAN", "all")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftir import (audit_programs, diff as manifest_diff,  # noqa: E402
                           load as manifest_load, DEFAULT_MANIFEST)
from tools.graftir.hlo import Program  # noqa: E402
from tools.graftir.programs import build_representative_set  # noqa: E402

FAILURES = []


def check(ok, what):
    tag = "ok" if ok else "FAIL"
    print("  [%s] %s" % (tag, what))
    if not ok:
        FAILURES.append(what)


def clone(p, text):
    """A copy of Program *p* with mutated HLO text, declarations kept."""
    return Program(p.subsystem, p.name, text, model=p.model,
                   donated=p.donated, dtype_policy=p.dtype_policy,
                   hot_path=p.hot_path, bucket_rows=p.bucket_rows,
                   natural_rows=p.natural_rows, budget=p.budget,
                   suppress=p.suppress, f32_allow=p.f32_allow)


def main():
    print("== graftir smoke: lowering representative set ==")
    programs = build_representative_set()
    by_key = {p.key(): p for p in programs}
    print("  programs: %s" % ", ".join(sorted(by_key)))

    # -- 1. shipped tree must be clean ---------------------------------
    print("== clean pass (rules + manifest) ==")
    engine, findings = audit_programs(programs)
    check(engine.stats["new"] == 0,
          "rules clean on shipped tree (new=%d)" % engine.stats["new"])
    rows, violations = manifest_diff(programs,
                                     manifest_load(DEFAULT_MANIFEST))
    bad = [r for r in rows if r["status"] != "ok"]
    check(not violations and not bad,
          "manifest diff all-ok (%d row(s), %d violation(s))"
          % (len(rows), len(violations)))

    # -- 2. seeded 2x cost regression must fail the manifest check -----
    print("== seeded 2x cost regression ==")
    victim = by_key["serve/predict/b8"]
    doubled = "\n".join(
        line + "\n" + line if ("dot_general" in line or
                               "dot " in line) else line
        for line in victim.text.splitlines())
    seeded = [clone(p, doubled) if p is victim else p for p in programs]
    _, violations = manifest_diff(seeded, manifest_load(DEFAULT_MANIFEST))
    hits = [v for v in violations
            if "serve/predict/b8" in v and "grew" in v]
    check(bool(hits),
          "manifest names the grown program (%s)"
          % (hits[0] if hits else "no violation raised"))

    # -- 3. stripped donation must raise GI001 -------------------------
    print("== stripped donation ==")
    victim = by_key["train/fused_step"]
    check(victim.donated_args() > 0,
          "fused step carries donation attrs before the strip (%d)"
          % victim.donated_args())
    stripped = (victim.text
                .replace("tf.aliasing_output", "tf.stripped_attr")
                .replace("jax.buffer_donor", "jax.stripped_attr"))
    _, new = audit_programs([clone(victim, stripped)],
                            rules=["GI001"], use_baseline=False)
    hits = [f for f in new if f.rule == "GI001"
            and f.program.key() == "train/fused_step"]
    check(bool(hits),
          "GI001 names the stripped program (%s)"
          % (hits[0].message if hits else "no finding raised"))

    # -- 4. injected f64 must raise GI002 ------------------------------
    print("== injected f64 ==")
    victim = by_key["decode/tick/S2"]
    poisoned = (victim.text +
                "\n  %smuggled = stablehlo.constant dense<0.0> "
                ": tensor<4xf64>\n")
    _, new = audit_programs([clone(victim, poisoned)],
                            rules=["GI002"], use_baseline=False)
    hits = [f for f in new if f.rule == "GI002"
            and f.program.key() == "decode/tick/S2"]
    check(bool(hits),
          "GI002 names the f64 program (%s)"
          % (hits[0].message if hits else "no finding raised"))

    if FAILURES:
        print("graftir smoke: %d FAILURE(s):" % len(FAILURES))
        for f in FAILURES:
            print("  - %s" % f)
        return 1
    print("graftir: programs=%d findings=%d ok"
          % (len(programs), engine.stats["new"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
