"""Detection op tests — numpy oracles for NMS/prior/target
(reference strategy: tests/python/unittest/test_operator.py multibox +
bounding_box cases)."""

import numpy as np

import mxnet_tpu as mx

nd = mx.nd


def _np_iou(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = iw * ih
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) \
        - inter
    return inter / ua if ua > 0 else 0.0


def _np_nms(rows, thresh, coord_start=2, score_index=1, valid_thresh=0.0):
    """Greedy NMS oracle: returns surviving rows desc-by-score, rest -1."""
    order = sorted(range(len(rows)),
                   key=lambda i: -rows[i][score_index])
    keep = []
    for i in order:
        if rows[i][score_index] <= valid_thresh:
            continue
        box = rows[i][coord_start:coord_start + 4]
        if any(_np_iou(box, rows[j][coord_start:coord_start + 4]) >
               thresh for j in keep):
            continue
        keep.append(i)
    out = np.full_like(rows, -1.0)
    for k, i in enumerate(keep):
        out[k] = rows[i]
    return out


def test_multibox_prior_matches_reference_math():
    data = nd.array(np.zeros((1, 3, 2, 3), np.float32))
    out = nd.contrib_box = mx.nd.MultiBoxPrior(
        data, sizes=(0.5, 0.3), ratios=(1.0, 2.0))
    out = out.asnumpy()
    assert out.shape == (1, 2 * 3 * 3, 4)
    # first anchor at cell (0,0): center (0.5/3, 0.5/2), size 0.5
    cx, cy = 0.5 / 3, 0.5 / 2
    w = 0.5 * 2 / 3 / 2  # size * in_h/in_w / 2
    h = 0.5 / 2
    np.testing.assert_allclose(out[0, 0], [cx - w, cy - h, cx + w,
                                           cy + h], rtol=1e-5)
    # third anchor: ratio 2, size 0.5: w=size*inh/inw*sqrt(2)/2
    sr = np.sqrt(2.0)
    w2 = 0.5 * 2 / 3 * sr / 2
    h2 = 0.5 / sr / 2
    np.testing.assert_allclose(
        out[0, 2], [cx - w2, cy - h2, cx + w2, cy + h2], rtol=1e-5)


def test_box_nms_matches_numpy():
    rs = np.random.RandomState(0)
    N = 20
    rows = np.zeros((N, 6), np.float32)
    ctr = rs.uniform(0.2, 0.8, (N, 2))
    wh = rs.uniform(0.05, 0.3, (N, 2))
    rows[:, 2] = ctr[:, 0] - wh[:, 0]
    rows[:, 3] = ctr[:, 1] - wh[:, 1]
    rows[:, 4] = ctr[:, 0] + wh[:, 0]
    rows[:, 5] = ctr[:, 1] + wh[:, 1]
    rows[:, 1] = rs.uniform(0.1, 1.0, N)
    rows[:, 0] = 0
    got = mx.nd.box_nms(nd.array(rows[None]), overlap_thresh=0.5,
                        force_suppress=True).asnumpy()[0]
    want = _np_nms(rows, 0.5)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_box_nms_per_class():
    rows = np.array([
        # two same-position boxes, different classes: both survive
        [0, 0.9, 0.1, 0.1, 0.5, 0.5],
        [1, 0.8, 0.1, 0.1, 0.5, 0.5],
        # same class as row 0, overlapping: suppressed
        [0, 0.7, 0.12, 0.12, 0.5, 0.5],
    ], np.float32)
    got = mx.nd.box_nms(nd.array(rows[None]), overlap_thresh=0.5,
                        id_index=0, force_suppress=False).asnumpy()[0]
    assert (got[0] == rows[0]).all()
    assert (got[1] == rows[1]).all()
    assert (got[2] == -1).all()


def test_box_iou():
    a = np.array([[0, 0, 2, 2]], np.float32)
    b = np.array([[1, 1, 3, 3], [0, 0, 2, 2], [5, 5, 6, 6]], np.float32)
    got = mx.nd.box_iou(nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(got[0], [1.0 / 7, 1.0, 0.0], rtol=1e-5)


def test_multibox_target_basic():
    """Single gt box perfectly matching anchor 1 -> positive with
    encoded zero offsets; others negative."""
    anchors = np.array([[[0.0, 0.0, 0.3, 0.3],
                         [0.4, 0.4, 0.8, 0.8],
                         [0.0, 0.6, 0.3, 0.9]]], np.float32)
    labels = np.array([[[2.0, 0.4, 0.4, 0.8, 0.8]]], np.float32)
    cls_preds = np.zeros((1, 4, 3), np.float32)
    loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(
        nd.array(anchors), nd.array(labels), nd.array(cls_preds))
    cls_t = cls_t.asnumpy()[0]
    np.testing.assert_allclose(cls_t, [0.0, 3.0, 0.0])  # cls 2 -> 3
    loc_m = loc_m.asnumpy()[0].reshape(3, 4)
    np.testing.assert_allclose(loc_m, [[0] * 4, [1] * 4, [0] * 4])
    loc_t = loc_t.asnumpy()[0].reshape(3, 4)
    np.testing.assert_allclose(loc_t[1], np.zeros(4), atol=1e-5)


def test_multibox_target_hard_negative_mining():
    anchors = np.array([[[0.0, 0.0, 0.3, 0.3],
                         [0.4, 0.4, 0.8, 0.8],
                         [0.0, 0.6, 0.3, 0.9],
                         [0.6, 0.0, 0.9, 0.3]]], np.float32)
    labels = np.array([[[1.0, 0.4, 0.4, 0.8, 0.8]]], np.float32)
    cls_preds = np.zeros((1, 3, 4), np.float32)
    # anchor 3 has LOW background score -> hardest negative
    cls_preds[0, 0] = [5.0, 5.0, 5.0, -5.0]
    loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(
        nd.array(anchors), nd.array(labels), nd.array(cls_preds),
        negative_mining_ratio=1.0, negative_mining_thresh=0.5)
    cls_t = cls_t.asnumpy()[0]
    assert cls_t[1] == 2.0          # positive
    assert cls_t[3] == 0.0          # hardest negative selected
    assert cls_t[0] == -1.0 and cls_t[2] == -1.0  # ignored


def test_multibox_detection_decode_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.5, 0.5, 0.9, 0.9]]], np.float32)
    # zero offsets -> boxes == anchors
    loc = np.zeros((1, 8), np.float32)
    cls_prob = np.array([[[0.1, 0.2],     # background
                          [0.8, 0.1],     # class 0
                          [0.1, 0.7]]], np.float32)  # class 1
    out = mx.nd.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc), nd.array(anchors),
        nms_threshold=0.5).asnumpy()[0]
    got = {tuple(round(float(v), 3) for v in r[2:]):
           (float(r[0]), round(float(r[1]), 3)) for r in out
           if r[0] >= 0}
    assert got[(0.1, 0.1, 0.3, 0.3)] == (0.0, 0.8)
    assert got[(0.5, 0.5, 0.9, 0.9)] == (1.0, 0.7)


def test_roi_align_shapes_and_constant():
    data = np.ones((1, 2, 8, 8), np.float32) * 3.0
    rois = np.array([[0, 0, 0, 4, 4]], np.float32)
    out = mx.nd.ROIAlign(nd.array(data), nd.array(rois),
                         pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out.asnumpy(), 3.0, rtol=1e-5)


def test_proposal_shapes():
    N, A, H, W = 1, 3, 4, 4
    rs = np.random.RandomState(0)
    cls_prob = rs.rand(N, 2 * A, H, W).astype(np.float32)
    bbox_pred = rs.randn(N, 4 * A, H, W).astype(np.float32) * 0.1
    im_info = np.array([[64, 64, 1.0]], np.float32)
    rois = mx.nd.Proposal(nd.array(cls_prob), nd.array(bbox_pred),
                          nd.array(im_info), rpn_post_nms_top_n=10,
                          scales=(2,), ratios=(0.5, 1, 2),
                          feature_stride=16, rpn_min_size=4)
    assert rois.shape == (10, 5)
    r = rois.asnumpy()
    assert (r[:, 1:] >= 0).all() and (r[:, 1:] <= 63).all()


def test_ssd300_reference_anchor_grid():
    """The SSD-300/VGG16-reduced graph reproduces the reference's
    anchor geometry: 8732 boxes over six scales, detection output
    (B, 8732, 6), and the training graph's target/loss heads infer
    cleanly (example/ssd/symbol parity at the architecture level)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples"))
    from ssd_model import build_ssd300_infer, build_ssd300_train

    infer = build_ssd300_infer(num_classes=20)
    _, outs, _ = infer.infer_shape(data0=(2, 3, 300, 300))
    assert outs == [(2, 8732, 6)]

    train = build_ssd300_train(num_classes=20)
    _, touts, _ = train.infer_shape(data0=(2, 3, 300, 300),
                                    label=(2, 1, 5))
    # cls softmax over (B*A, C+1), smooth-l1 over (B, A*4), anchors
    assert touts[0] == (2 * 8732, 21)
    assert touts[1] == (2, 8732 * 4)
    assert touts[2] == (1, 8732, 4)
