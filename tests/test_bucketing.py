"""BucketingModule + symbolic RNN cell tests.

Reference: tests/python/unittest/test_module.py (bucketing cases),
python/mxnet/rnn/rnn_cell.py behavior, example/rnn/bucketing.
"""

import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _np_sigmoid(x):
    return 1 / (1 + np.exp(-x))


def test_rnncell_unroll_matches_numpy():
    cell = mx.rnn.RNNCell(num_hidden=4, activation="tanh", prefix="r_")
    outputs, states = cell.unroll(3, inputs=mx.sym.var("x"),
                                  layout="NTC", merge_outputs=True)
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 5).astype(np.float32)
    iw = rs.randn(4, 5).astype(np.float32)
    ib = rs.randn(4).astype(np.float32)
    hw = rs.randn(4, 4).astype(np.float32)
    hb = rs.randn(4).astype(np.float32)
    exe = outputs.bind(args={"x": nd.array(x),
                             "r_i2h_weight": nd.array(iw),
                             "r_i2h_bias": nd.array(ib),
                             "r_h2h_weight": nd.array(hw),
                             "r_h2h_bias": nd.array(hb)})
    out = exe.forward()[0].asnumpy()
    h = np.zeros((2, 4), np.float32)
    expect = []
    for t in range(3):
        h = np.tanh(x[:, t] @ iw.T + ib + h @ hw.T + hb)
        expect.append(h)
    np.testing.assert_allclose(out, np.stack(expect, 1), rtol=1e-5,
                               atol=1e-5)


def test_lstmcell_gru_shapes_and_gradients_flow():
    for cell in (mx.rnn.LSTMCell(num_hidden=6, prefix="l_"),
                 mx.rnn.GRUCell(num_hidden=6, prefix="g_")):
        outputs, states = cell.unroll(4, inputs=mx.sym.var("x"),
                                      layout="NTC", merge_outputs=True)
        loss = mx.sym.sum(outputs)
        exe = loss.simple_bind(x=(2, 4, 3), grad_req="write")
        rs = np.random.RandomState(1)
        for name, arr in exe.arg_dict.items():
            if name != "x":
                arr[:] = nd.array(rs.randn(*arr.shape).astype(
                    np.float32) * 0.2)
        exe.forward(is_train=True, x=nd.array(
            rs.randn(2, 4, 3).astype(np.float32)))
        exe.backward(out_grads=[nd.ones(())])
        gsum = sum(float(np.abs(g.asnumpy()).sum())
                   for n, g in exe.grad_dict.items() if n != "x")
        assert np.isfinite(gsum) and gsum > 0


def test_sequential_and_modifier_cells():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden=4, prefix="s0_"))
    stack.add(mx.rnn.ResidualCell(
        mx.rnn.LSTMCell(num_hidden=4, prefix="s1_")))
    outputs, states = stack.unroll(3, inputs=mx.sym.var("x"),
                                   layout="NTC", merge_outputs=True)
    exe = outputs.simple_bind(x=(2, 3, 4))
    assert exe.forward()[0].shape == (2, 3, 4)
    assert len(states) == 4  # 2 cells x (h, c)


def test_bidirectional_cell():
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.RNNCell(num_hidden=3, prefix="fw_"),
        mx.rnn.RNNCell(num_hidden=3, prefix="bw_"))
    outputs, states = bi.unroll(4, inputs=mx.sym.var("x"),
                                layout="NTC", merge_outputs=True)
    exe = outputs.simple_bind(x=(2, 4, 5))
    assert exe.forward()[0].shape == (2, 4, 6)


def test_fused_cell_unroll():
    fused = mx.rnn.FusedRNNCell(num_hidden=5, num_layers=2, mode="lstm",
                                prefix="f_")
    outputs, states = fused.unroll(6, inputs=mx.sym.var("x"),
                                   layout="NTC")
    exe = outputs.simple_bind(x=(3, 6, 4))
    assert exe.forward()[0].shape == (3, 6, 5)
    assert states[0].infer_shape(x=(3, 6, 4))[1]


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [4, 5, 6, 7, 8], [1, 1], [2, 2, 2, 2, 2],
                 [3, 3, 3]] * 4
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[3, 5], invalid_label=0,
                                   shuffle=False)
    seen = set()
    for batch in it:
        seen.add(batch.bucket_key)
        data = batch.data[0].asnumpy()
        label = batch.label[0].asnumpy()
        assert data.shape == (4, batch.bucket_key)
        # label is input shifted left, padded with invalid
        np.testing.assert_allclose(label[:, :-1], data[:, 1:])
        np.testing.assert_allclose(label[:, -1], 0)
    assert seen == {3, 5}


def _lm_module(vocab=20, hidden=16):
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden=hidden, prefix="lstm_"))

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab,
                                 output_dim=8, name="embed")
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.reshape(outputs, shape=(-1, hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab,
                                     name="pred")
        label = mx.sym.reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(data=pred, label=label,
                                    name="softmax"), ("data",), \
            ("softmax_label",)

    return mx.mod.BucketingModule(sym_gen=sym_gen, default_bucket_key=8)


def test_bucketing_module_shares_params_across_buckets():
    from mxnet_tpu.io.io import DataBatch, DataDesc
    mod = _lm_module()
    mod.bind(data_shapes=[("data", (2, 8))],
             label_shapes=[("softmax_label", (2, 8))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    def batch(seq_len):
        rs = np.random.RandomState(seq_len)
        d = rs.randint(0, 20, (2, seq_len)).astype(np.float32)
        return DataBatch(
            data=[nd.array(d)], label=[nd.array(d)], bucket_key=seq_len,
            provide_data=[DataDesc("data", (2, seq_len))],
            provide_label=[DataDesc("softmax_label", (2, seq_len))])

    mod.forward_backward(batch(4))
    mod.update()
    m4 = mod._buckets[4]
    m8 = mod._buckets[8]
    # same NDArray objects: an update through bucket 4 IS visible in 8
    for name in m4._exec_group.param_names:
        assert m4._exec_group.execs[0].arg_dict[name] is \
            m8._exec_group.execs[0].arg_dict[name]
    # one shared updater (borrowed optimizer)
    assert m4._updater is m8._updater
    # training through alternating buckets moves the shared weights
    w0 = m8._exec_group.execs[0].arg_dict["pred_weight"].asnumpy().copy()
    mod.forward_backward(batch(8))
    mod.update()
    w1 = m8._exec_group.execs[0].arg_dict["pred_weight"].asnumpy()
    assert not np.allclose(w0, w1)
    # executor cache: no rebind for an already-seen bucket
    before = dict(mod._buckets)
    mod.forward_backward(batch(4))
    assert mod._buckets[4] is before[4]


def test_lm_example_perplexity_drops():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "examples/train_lm.py", "--num-epochs", "4",
         "--num-sentences", "400", "--max-perplexity", "12"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
