"""Randomized scheduler-equivalence stress (reference:
tests/cpp/engine/threaded_engine_test.cc — randomized dependency
workloads through all engines asserting identical results; SURVEY §5.2).

Random op graphs run three ways — imperative eager, whole-graph jit
(bulk), per-node non-bulk — must agree bit-for-bit-ish; this is the
TPU-era analogue of racing the threaded engine against NaiveEngine."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.runtime import engine

_UNARY = ["relu", "sigmoid", "tanh", "exp_neg", "sqrt_abs", "square"]
_BINARY = ["add", "mul", "sub", "max"]


def _apply_unary(op, a):
    if op == "relu":
        return mx.nd.relu(a)
    if op == "sigmoid":
        return mx.nd.sigmoid(a)
    if op == "tanh":
        return mx.nd.tanh(a)
    if op == "exp_neg":
        return mx.nd.exp(-a)
    if op == "sqrt_abs":
        return mx.nd.sqrt(mx.nd.abs(a))
    return a * a


def _apply_binary(op, a, b):
    if op == "add":
        return a + b
    if op == "mul":
        return a * b
    if op == "sub":
        return a - b
    return mx.nd.broadcast_maximum(a, b)


def _random_graph_sym(rng, n_inputs=3, n_nodes=12):
    """Random DAG over symbols; returns (symbol, input names)."""
    names = ["in%d" % i for i in range(n_inputs)]
    pool = [mx.sym.var(n) for n in names]
    for i in range(n_nodes):
        if rng.rand() < 0.5 and len(pool) >= 2:
            ia, ib = rng.randint(0, len(pool), 2)
            op = _BINARY[rng.randint(len(_BINARY))]
            if op == "add":
                s = pool[ia] + pool[ib]
            elif op == "mul":
                s = pool[ia] * pool[ib]
            elif op == "sub":
                s = pool[ia] - pool[ib]
            else:
                s = mx.sym.broadcast_maximum(pool[ia], pool[ib])
        else:
            ia = rng.randint(len(pool))
            op = _UNARY[rng.randint(len(_UNARY))]
            if op == "relu":
                s = mx.sym.Activation(pool[ia], act_type="relu")
            elif op == "sigmoid":
                s = mx.sym.Activation(pool[ia], act_type="sigmoid")
            elif op == "tanh":
                s = mx.sym.Activation(pool[ia], act_type="tanh")
            elif op == "exp_neg":
                s = mx.sym.exp(-pool[ia])
            elif op == "sqrt_abs":
                s = mx.sym.sqrt(mx.sym.abs(pool[ia]))
            else:
                s = pool[ia] * pool[ia]
        pool.append(s)
    return pool[-1], names


@pytest.mark.parametrize("seed", range(6))
def test_random_graph_bulk_vs_per_node_vs_imperative(seed):
    rng = np.random.RandomState(seed)
    sym, names = _random_graph_sym(rng)
    vals = {n: rng.randn(4, 5).astype(np.float32) * 0.5 for n in names}
    args = {n: mx.nd.array(v) for n, v in vals.items()}

    ex = sym.bind(mx.cpu(), dict(args))
    bulk_out = ex.forward()[0].asnumpy()

    with engine.bulk(0):
        per_node_out = ex.forward()[0].asnumpy()

    np.testing.assert_allclose(per_node_out, bulk_out, rtol=1e-6,
                               atol=1e-6)

    # imperative replay of the same graph through the nd API
    def replay(node, cache):
        if id(node) in cache:
            return cache[id(node)]
        if node.is_var:
            out = args[node.name]
        else:
            ins = [replay(s, cache) for s, _ in node.inputs]
            from mxnet_tpu.ndarray.ndarray import imperative_invoke
            out = imperative_invoke(node.op.name, *ins, **node.params)
            if isinstance(out, (list, tuple)):
                out = out[0]
        cache[id(node)] = out
        return out

    node, _slot = sym._outputs[0]
    imp_out = replay(node, {}).asnumpy()
    np.testing.assert_allclose(imp_out, bulk_out, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("seed", range(3))
def test_random_graph_gradients_bulk_vs_naive(seed):
    """Gradients agree between normal async mode and naive (synchronous)
    mode — the determinism escape hatch must not change results."""
    rng = np.random.RandomState(100 + seed)
    sym, names = _random_graph_sym(rng, n_nodes=8)
    loss = mx.sym.sum(sym)
    vals = {n: rng.randn(3, 4).astype(np.float32) * 0.5 for n in names}

    def run():
        args = {n: mx.nd.array(v) for n, v in vals.items()}
        grads = {n: mx.nd.zeros(v.shape) for n, v in vals.items()}
        ex = loss.bind(mx.cpu(), args, args_grad=grads)
        ex.forward(is_train=True)
        ex.backward(mx.nd.ones(()))
        return {n: g.asnumpy() for n, g in ex.grad_dict.items()}

    normal = run()
    with engine.naive_mode():
        naive = run()
    for n in normal:
        np.testing.assert_allclose(naive[n], normal[n], rtol=1e-6,
                                   atol=1e-6)
