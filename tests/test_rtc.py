"""Runtime kernel compilation tests (reference:
tests/python/gpu/test_rtc.py — CudaModule compile + launch)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


_SOURCE = """
def axpy(a, x, y):
    return a * x + y


def split_halves(x):
    n = x.shape[0] // 2
    return x[:n], x[n:]


def pallas_double(x):
    # a real pallas kernel, interpret mode so it runs on any backend
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True,
    )(x)
"""


def test_module_get_kernel_and_launch():
    mod = mx.rtc.Module(_SOURCE)
    axpy = mod.get_kernel("axpy")
    x = mx.nd.array(np.arange(6, dtype=np.float32))
    y = mx.nd.ones((6,))
    out = axpy(mx.nd.array(np.full((6,), 2.0, np.float32)), x, y)
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(6) * 2.0 + 1.0, rtol=1e-6)
    # reference-shaped launch() accepts grid/block dims
    out2 = axpy.launch([mx.nd.ones((6,)) * 3.0, x, y], mx.cpu(),
                       (1, 1, 1), (6, 1, 1))
    np.testing.assert_allclose(out2.asnumpy(),
                               np.arange(6) * 3.0 + 1.0, rtol=1e-6)


def test_module_multi_output_kernel():
    mod = mx.rtc.Module(_SOURCE)
    k = mod.get_kernel("split_halves")
    outs = k(mx.nd.array(np.arange(8, dtype=np.float32)))
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].asnumpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(outs[1].asnumpy(), [4, 5, 6, 7])


def test_module_pallas_kernel():
    mod = mx.rtc.Module(_SOURCE)
    k = mod.get_kernel("pallas_double")
    x = mx.nd.array(np.arange(16, dtype=np.float32).reshape(2, 8))
    np.testing.assert_allclose(k(x).asnumpy(),
                               np.arange(16).reshape(2, 8) * 2.0,
                               rtol=1e-6)


def test_module_errors():
    with pytest.raises(MXNetError, match="failed to compile"):
        mx.rtc.Module("def broken(:\n")
    mod = mx.rtc.Module(_SOURCE, exports=("axpy",))
    with pytest.raises(MXNetError, match="not exported"):
        mod.get_kernel("split_halves")
    with pytest.raises(MXNetError, match="not found"):
        mx.rtc.Module("x = 1").get_kernel("nope")


def test_register_op_reaches_nd_and_sym():
    @mx.rtc.register_op("_rtc_test_scale")
    def _rtc_test_scale(x, scale=2.0):
        return x * scale

    x = mx.nd.array(np.ones((3,), np.float32))
    np.testing.assert_allclose(
        mx.nd._rtc_test_scale(x, scale=5.0).asnumpy(), [5, 5, 5])
    # symbolic path through the executor
    s = mx.sym._rtc_test_scale(mx.sym.var("d"), scale=4.0)
    out = s.bind(mx.cpu(), {"d": x}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [4, 4, 4])
    # gradients flow (jax differentiates the registered fn)
    from mxnet_tpu import autograd
    a = mx.nd.array(np.array([1.0, 2.0], np.float32))
    a.attach_grad()
    with autograd.record():
        L = mx.nd.sum(mx.nd._rtc_test_scale(a, scale=3.0))
    L.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [3, 3], rtol=1e-6)


def test_kernel_ndarray_kwargs_unwrapped():
    mod = mx.rtc.Module(_SOURCE)
    axpy = mod.get_kernel("axpy")
    x = mx.nd.array(np.arange(4, dtype=np.float32))
    out = axpy(mx.nd.ones((4,)) * 2.0, x, y=mx.nd.ones((4,)))
    np.testing.assert_allclose(out.asnumpy(), np.arange(4) * 2.0 + 1.0,
                               rtol=1e-6)
