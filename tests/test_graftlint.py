"""graftlint static analyzer (tools/graftlint).

Covers: a positive and a negative fixture per rule (JG001–JG013),
suppression syntax, the baseline workflow, the CLI (exit codes, JSON,
scrapeable summary line), the guarantee that the shipped mxnet_tpu
tree is clean, the runtime registry cross-check (every register_op
entry holds the JG005 invariants), and regression tests for the real
findings the analyzer surfaced that this PR fixed.
"""

import json
import logging
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import LintEngine  # noqa: E402
from tools.graftlint.engine import parse_suppressions  # noqa: E402
from tools.graftlint.rules import ALL_RULES, RULE_DOCS  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd, sym  # noqa: E402
from mxnet_tpu.ops import registry as _reg  # noqa: E402


def lint(tmp_path, src, filename="mod.py", rules=None):
    """Lint one dedented snippet placed at mxnet_tpu/<filename> under a
    temp root; returns the list of NEW findings."""
    pkg = tmp_path / "mxnet_tpu"
    target = pkg / filename
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(src))
    eng = LintEngine([str(pkg)], rules=rules, use_baseline=False)
    findings = eng.run()
    return [f for f in findings if f.status == "new"]


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# per-rule fixtures: one positive and one negative each
# ---------------------------------------------------------------------------

def test_jg001_positive(tmp_path):
    fs = lint(tmp_path, """\
        import jax
        import numpy as np

        def f(x):
            np.asarray(x)
            return float(x) + x.item()

        jf = jax.jit(f)
        """, rules=["JG001"])
    assert len(fs) == 3, fs
    assert rule_ids(fs) == ["JG001"] * 3


def test_jg001_taint_propagates_through_calls(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def helper(y):
            return int(y)

        @jax.jit
        def entry(x):
            return helper(x)
        """, rules=["JG001"])
    assert len(fs) == 1 and "helper" in fs[0].message


def test_jg001_negative(tmp_path):
    fs = lint(tmp_path, """\
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            return x * int(n)      # n is static: concretizing is fine

        def not_traced(y):
            return float(y)        # never reaches a jit

        def shape_math(x):
            return int(x.shape[0])

        sf = jax.jit(shape_math)   # shapes are static under trace
        """, rules=["JG001"])
    assert fs == []


def test_jg002_positive(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def train(w, g):
            step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
            out = step(w, g)
            return out + w         # w's buffer was donated above
        """, rules=["JG002"])
    assert len(fs) == 1 and "'w'" in fs[0].message


def test_jg002_negative_rebind_kills(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def train(w, g):
            step = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
            w = step(w, g)         # rebinding makes later reads safe
            return w + g

        def no_donation(w, g):
            step = jax.jit(lambda a, b: a + b, donate_argnums=())
            out = step(w, g)
            return out + w
        """, rules=["JG002"])
    assert fs == []


def test_jg003_positive(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        _compiles = 0

        @jax.jit
        def f(x):
            global _compiles
            _compiles += 1
            print("tracing")
            return x
        """, rules=["JG003"])
    assert len(fs) == 2  # global write + print


def test_jg003_negative(tmp_path):
    fs = lint(tmp_path, """\
        def host_side(x):
            print("this never traces")
            return x

        def reader(x):
            global _cfg            # read-only global: harmless
            return x * _cfg

        import jax
        jr = jax.jit(reader)
        """, rules=["JG003"])
    assert fs == []


def test_jg004_positive(tmp_path):
    fs = lint(tmp_path, """\
        import time
        import jax

        @jax.jit
        def f(x):
            return x * time.time()     # burned in as a constant

        def build(fns):
            out = []
            for fn in fns:
                out.append(jax.jit(fn))    # fresh cache every iter
            return out
        """, rules=["JG004"])
    assert len(fs) == 2


def test_jg004_negative(tmp_path):
    fs = lint(tmp_path, """\
        import time
        import jax

        def wallclock():
            return time.time()         # host-side, never traced

        def build(fn):
            jitted = jax.jit(fn)       # once, outside any loop
            out = []
            for i in range(3):
                out.append(jitted(i))
            return out
        """, rules=["JG004"])
    assert fs == []


def test_jg005_positive(tmp_path):
    fs = lint(tmp_path, """\
        def register_op(*a, **k):
            def deco(fn):
                return fn
            return deco

        @register_op("bad_donate", num_outputs=2, donate=(5,))
        def bad(a, b, scale=1.0):
            return a * scale           # 1 return vs num_outputs=2

        @register_op("bad_rng", needs_rng=True)
        def bad_rng(data, other):
            return data + other
        """, rules=["JG005"])
    assert len(fs) == 3  # donate range + arity mismatch + rng param


def test_jg005_negative(tmp_path):
    fs = lint(tmp_path, """\
        def register_op(*a, **k):
            def deco(fn):
                return fn
            return deco

        @register_op("good", num_outputs=2, donate=(0, 1), needs_rng=True)
        def good(rng, a, b, scale=1.0):
            return a * scale, b

        @register_op("indeterminate", num_outputs=3)
        def indet(x):
            out = (x, x, x)
            return out                 # arity not a literal: skipped
        """, rules=["JG005"])
    assert fs == []


def test_jg006_positive(tmp_path):
    fs = lint(tmp_path, """\
        def dispatch(fn):
            try:
                return fn()
            except Exception:
                return None

        def dispatch2(fn):
            try:
                return fn()
            except:
                return None
        """, filename="executor.py", rules=["JG006"])
    assert len(fs) == 2


def test_jg006_negative(tmp_path):
    fs = lint(tmp_path, """\
        import logging

        def narrow(fn):
            try:
                return fn()
            except ValueError:
                return None

        def loud(fn):
            try:
                return fn()
            except Exception as e:
                logging.getLogger(__name__).debug("fell back: %s", e)
                return None

        def reraise(fn):
            try:
                return fn()
            except Exception:
                raise
        """, filename="executor.py", rules=["JG006"])
    assert fs == []


def test_jg005_optional_array_inputs_are_donatable(tmp_path):
    # input_names may extend past the required positionals with
    # optional array inputs (Convolution's bias=None); donating one is
    # legal — static rule must match registry.op_contract
    fs = lint(tmp_path, """\
        def register_op(*a, **k):
            def deco(fn):
                return fn
            return deco

        @register_op("opt_in", input_names=("weight", "grad", "bias"),
                     donate=(2,))
        def opt_in(weight, grad, bias=None, lr=0.1):
            return weight - lr * grad
        """, rules=["JG005"])
    assert fs == []


def test_rng_param_names_match_runtime_mirror():
    # the analyzer duplicates the rng-name set (it can't import the
    # jax-loading registry); keep the two in lockstep
    from tools.graftlint.rules import _RNG_PARAM_NAMES as static_names
    assert set(static_names) == set(_reg._RNG_PARAM_NAMES)


def test_single_file_scan_keeps_package_context(tmp_path):
    # scanning ONE file of a real package must keep the package-
    # qualified relpath, or dispatch-path scoping (JG006) silently
    # turns off in pre-commit single-file runs
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    target = pkg / "executor.py"
    target.write_text(
        "def dispatch(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except Exception:\n"
        "        return None\n")
    eng = LintEngine([str(target)], rules=["JG006"], use_baseline=False)
    fs = [f for f in eng.run() if f.status == "new"]
    assert len(fs) == 1
    assert fs[0].path == "mxnet_tpu/executor.py"


def test_jg006_scoped_to_dispatch_paths(tmp_path):
    # the same silent handler OUTSIDE a dispatch path is not flagged
    fs = lint(tmp_path, """\
        def metric_update(fn):
            try:
                return fn()
            except Exception:
                return None
        """, filename="metric.py", rules=["JG006"])
    assert fs == []


def test_jg007_positive(tmp_path):
    fs = lint(tmp_path, """\
        def bind(symbol, shapes={}, aug_list=[]):
            return symbol, shapes, aug_list
        """, rules=["JG007"])
    assert len(fs) == 2


def test_jg007_negative(tmp_path):
    fs = lint(tmp_path, """\
        def bind(symbol, shapes=None, aug_list=(), name=""):
            if shapes is None:
                shapes = {}
            return symbol, shapes, aug_list, name
        """, rules=["JG007"])
    assert fs == []


def test_jg008_positive(tmp_path):
    fs = lint(tmp_path, """\
        import jax
        import jax.numpy as jnp

        KERNEL = jnp.array([0.299, 0.587, 0.114])    # backend init!

        def f(x=jnp.zeros(3)):    # defaults evaluate at import too
            return x

        NDEV = jax.device_count()
        """, rules=["JG008"])
    assert len(fs) == 3


def test_jg008_negative(tmp_path):
    fs = lint(tmp_path, """\
        import jax
        import jax.numpy as jnp

        psum = jax.lax.psum                      # alias, no call
        TABLE = {"relu": lambda x: jnp.maximum(x, 0)}  # deferred

        def inside(x):
            return jnp.asarray(x)                # runs at call time
        """, rules=["JG008"])
    assert fs == []


def test_jg009_positive(tmp_path):
    fs = lint(tmp_path, """\
        import pickle

        def save_checkpoint(prefix, blob, states):
            with open(prefix + "-0000.params", "wb") as f:
                f.write(blob)
            with open(prefix + "-0000.states", "wb") as f:
                pickle.dump(states, f)
        """, rules=["JG009"])
    # two raw open()-for-write + one pickle.dump
    assert len(fs) == 3 and rule_ids(fs) == ["JG009"] * 3
    assert "atomic_write" in fs[0].message


def test_jg009_positive_np_savez(tmp_path):
    fs = lint(tmp_path, """\
        import numpy as np

        def dump_states(path, tree):
            np.savez(path + ".states", **tree)
        """, rules=["JG009"])
    assert len(fs) == 1 and "np.savez" in fs[0].message


def test_jg009_negative(tmp_path):
    fs = lint(tmp_path, """\
        from mxnet_tpu.resilience.checkpoint import atomic_write

        def save_checkpoint(prefix, blob):
            # routed through the atomic writer: fine
            atomic_write(prefix + "-0000.params", blob)

        def write_log(path, lines):
            # write-mode open, but no checkpoint/state artifact
            with open(path, "w") as f:
                f.writelines(lines)

        def load_checkpoint(prefix):
            # read-mode open of a checkpoint path: fine
            with open(prefix + "-0000.params", "rb") as f:
                return f.read()

        def compute_checkpoint_size(prefix):
            # persistence-flavored strings but no save-ish name
            return len(prefix + "-0000.params")
        """, rules=["JG009"])
    assert fs == []


def test_jg009_exempts_the_atomic_writer_itself(tmp_path):
    fs = lint(tmp_path, """\
        def atomic_write(path, data):
            tmp = path + ".tmp"        # the checkpoint writer itself
            with open(tmp, "wb") as f:
                f.write(data)
        """, filename="resilience/checkpoint.py", rules=["JG009"])
    assert fs == []


# ---------------------------------------------------------------------------
def test_jg010_positive(tmp_path):
    """An attribute written under self.lock in one method and bare in
    another: the bare write is the finding."""
    fs = lint(tmp_path, """\
        import threading

        class Server:
            def __init__(self):
                self.lock = threading.Lock()
                self.updater = None      # construction: exempt

            def apply(self, fn):
                with self.lock:
                    self.updater = fn    # guarded write

            def set_opt(self, fn):
                self.updater = fn        # bare write -> JG010
        """, rules=["JG010"])
    assert rule_ids(fs) == ["JG010"]
    assert "updater" in fs[0].message and "self.lock" in fs[0].message


def test_jg010_positive_sanitizer_factory_and_subscript(tmp_path):
    """Locks created via the sanitizer bridge count, and subscript
    writes (self.store[k] = v) are writes."""
    fs = lint(tmp_path, """\
        from mxnet_tpu import sanitizer as _san

        class Store:
            def __init__(self):
                self.mu = _san.rlock()
                self.store = {}

            def put(self, k, v):
                with self.mu:
                    self.store[k] = v

            def drop(self, k):
                self.store[k] = None     # bare subscript write -> JG010
        """, rules=["JG010"])
    assert rule_ids(fs) == ["JG010"]


def test_jg010_negative(tmp_path):
    fs = lint(tmp_path, """\
        import threading

        class Clean:
            def __init__(self):
                self.lock = threading.Lock()
                self.cv = threading.Condition()
                self.state = 0
                self.rounds = {}
                self.solo = None

            def bump(self):
                with self.lock:
                    self.state += 1      # always guarded

            def arrive(self, r):
                with self.cv:
                    self.rounds = {r: 1}  # guarded by the condition

            def rebind(self, v):
                self.solo = v            # never guarded anywhere: no
                                         # lock claims this attr

        class NoLocks:
            def __init__(self):
                self.x = 0

            def set(self, v):
                self.x = v               # class has no locks at all
        """, rules=["JG010"])
    assert fs == []


def test_jg011_positive_unowned_thread(tmp_path):
    fs = lint(tmp_path, """\
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()                    # no daemon, never joined
        """, rules=["JG011"])
    assert rule_ids(fs) == ["JG011"]
    assert "daemon" in fs[0].message


def test_jg011_positive_shared_mutable_args(tmp_path):
    fs = lint(tmp_path, """\
        import threading

        RESULTS = []

        def collect(fn):
            t = threading.Thread(target=fn, args=(RESULTS,),
                                 daemon=True)
            t.start()
        """, rules=["JG011"])
    assert rule_ids(fs) == ["JG011"]
    assert "RESULTS" in fs[0].message


def test_jg011_unrelated_join_does_not_count_as_ownership(tmp_path):
    """os.path.join / str.join in the same scope must not satisfy the
    join-ownership check — it is anchored to the thread's bound name."""
    fs = lint(tmp_path, """\
        import os
        import threading

        def spawn(fn, a, b):
            p = os.path.join(a, b)
            parts = ",".join([a, b])
            t = threading.Thread(target=fn)
            t.start()
            return p, parts
        """, rules=["JG011"])
    assert rule_ids(fs) == ["JG011"]


def test_jg010_acquire_release_counts_as_guarded(tmp_path):
    """The acquire()/try/finally/release() idiom guards its writes just
    like a with-block — no false positive."""
    fs = lint(tmp_path, """\
        import threading

        class Disciplined:
            def __init__(self):
                self.lock = threading.Lock()
                self.state = 0

            def with_style(self, v):
                with self.lock:
                    self.state = v

            def acquire_style(self, v):
                self.lock.acquire()
                try:
                    self.state = v
                finally:
                    self.lock.release()
        """, rules=["JG010"])
    assert fs == []


def test_jg011_negative(tmp_path):
    fs = lint(tmp_path, """\
        import threading
        from mxnet_tpu import sanitizer as _san

        def daemonized(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()

        def joined(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        class Owner:
            def start(self, fn):
                self._t = _san.thread(target=fn)
                self._t.start()

            def stop(self):
                self._t.join()           # class-scope ownership

        def local_args(fn):
            items = [1, 2]               # function-local, not shared
            t = threading.Thread(target=fn, args=(items,), daemon=True)
            t.start()
        """, rules=["JG011"])
    assert fs == []


def test_jg012_positive_deadline_and_elapsed(tmp_path):
    fs = lint(tmp_path, """\
        import time

        def wait_deadline(pending):
            deadline = time.time() + 30
            while pending and time.time() < deadline:
                pending.pop()

        def wait_elapsed(start_evicting, timeout):
            start = time.time()
            while True:
                if time.time() - start > timeout:
                    return start_evicting()

        def stamp_then_compare(table, node, timeout):
            now = time.time()
            return [n for n, ts in table.items()
                    if now - ts > timeout]
        """, rules=["JG012"])
    # wait_deadline compares twice (the assign feeds one via the
    # name, the while header holds a direct call)
    assert len(fs) >= 3
    assert rule_ids(fs) == ["JG012"] * len(fs)
    assert "monotonic" in fs[0].message


def test_jg012_positive_aliased_import(tmp_path):
    fs = lint(tmp_path, """\
        import time as _time

        def poll(done):
            end = _time.time() + 5
            while not done() and _time.time() < end:
                pass
        """, rules=["JG012"])
    assert len(fs) >= 1


def test_jg012_negative(tmp_path):
    fs = lint(tmp_path, """\
        import time

        def timestamp_field(rec):
            rec["ts"] = time.time()      # wall time AS a timestamp: fine
            return rec

        def epoch_token():
            return int(time.time() * 1000) & 0xFFFF   # token, no compare

        def monotonic_deadline(pending, timeout):
            deadline = time.monotonic() + timeout
            while pending and time.monotonic() < deadline:
                pending.pop()

        def perf_span():
            t0 = time.perf_counter()
            return time.perf_counter() - t0 > 1.0
        """, rules=["JG012"])
    assert fs == []


def test_jg013_positive_sync_in_step_loop(tmp_path):
    fs = lint(tmp_path, """\
        def train(mod, it, metric):
            for batch in it:
                mod.forward_backward_update(batch)
                loss = mod.get_outputs()[0].asnumpy()   # per-step sync
                metric.update(loss)

        def serve(predictor, reqs):
            while reqs:
                out = predictor.predict_batch(reqs.pop())
                print(out.item())                       # per-step sync
        """, rules=["JG013"])
    assert len(fs) == 2, fs
    assert rule_ids(fs) == ["JG013"] * 2
    assert "dispatches steps" in fs[0].message
    assert "MXNET_GUARD_READBACK_LAG" in fs[0].message


def test_jg013_positive_block_until_ready(tmp_path):
    fs = lint(tmp_path, """\
        def fit_epoch(trainer, batches):
            for x, y in batches:
                loss = trainer.fit_batch(x, y)
                loss.block_until_ready()
        """, rules=["JG013"])
    assert len(fs) == 1
    assert ".block_until_ready()" in fs[0].message


def test_jg013_negative(tmp_path):
    fs = lint(tmp_path, """\
        def train_overlapped(mod, it, metric):
            losses = []
            for batch in it:
                mod.forward_backward_update(batch)
                losses.append(mod.get_outputs()[0])
            # sync hoisted out of the loop: one drain at the end
            return [l.asnumpy() for l in losses]

        def decode_loop(batches):
            # syncs in a loop that dispatches no steps: fine
            return [b.asnumpy() for b in batches]

        def launcher(mod, it):
            # a def inside the loop runs when CALLED, not per step
            for batch in it:
                def flush():
                    return mod.get_outputs()[0].asnumpy()
                mod.forward_backward_update(batch)
        """, rules=["JG013"])
    assert fs == []


def test_jg014_positive_chained_and_split(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def build(fn, avals):
            return jax.jit(fn).lower(*avals).compile()

        def build_split(fn, avals):
            lowered = jax.jit(fn).lower(*avals)
            text = lowered.as_text()
            return lowered.compile(), text
        """, rules=["JG014"])
    assert len(fs) == 2, fs
    assert rule_ids(fs) == ["JG014"] * 2
    assert "graftir" in fs[0].message
    assert "audited producers" in fs[0].message


def test_jg014_negative_allowlisted_producer(tmp_path):
    # the audited producers carry the MXNET_IR_AUDIT hooks — their
    # build sites are the allowlist
    fs = lint(tmp_path, """\
        import jax

        def ensure_program(jitted, avals):
            lowered = jitted.lower(*avals)
            return lowered.compile()
        """, filename="serve/predictor.py", rules=["JG014"])
    assert fs == []


def test_jg014_negative_benign_compiles_and_lower_only(tmp_path):
    fs = lint(tmp_path, """\
        import re

        def scan(s):
            pat = re.compile("x+")        # stdlib compile: fine
            return pat.match(s.lower())   # str.lower: fine

        def inspect(jitted, avals):
            return jitted.lower(*avals).as_text()   # lower-only: fine
        """, rules=["JG014"])
    assert fs == []


def test_jg015_positive_if_guarded_wait(tmp_path):
    fs = lint(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self.cv = threading.Condition()
                self.ready = False

            def get(self):
                with self.cv:
                    if not self.ready:
                        self.cv.wait()
                    return 1

            def get_else_arm(self):
                with self.cv:
                    if self.ready:
                        pass
                    else:
                        self.cv.wait(timeout=1.0)
        """, rules=["JG015"])
    assert len(fs) == 2, fs
    assert rule_ids(fs) == ["JG015"] * 2
    assert "lost" in fs[0].message
    assert "while" in fs[0].message


def test_jg015_negative_while_and_wait_for(tmp_path):
    fs = lint(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self.cv = threading.Condition()
                self.ready = False

            def get(self):
                with self.cv:
                    while not self.ready:
                        self.cv.wait()

            def get_pred(self):
                with self.cv:
                    if not self.ready:
                        self.cv.wait_for(lambda: self.ready)

            def get_loop_recheck(self):
                with self.cv:
                    while True:
                        if self.ready:
                            break
                        self.cv.wait(timeout=0.5)

            def other_event(self, ev):
                done = threading.Event()
                with self.cv:
                    if not self.ready:
                        done.wait()   # not the condition: out of scope
        """, rules=["JG015"])
    assert fs == []


# ---------------------------------------------------------------------------
# suppression + baseline workflow
# ---------------------------------------------------------------------------

def test_inline_suppression(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def f(x):
            return float(x)  # graftlint: disable=JG001

        jf = jax.jit(f)
        """, rules=["JG001"])
    assert fs == []


def test_suppression_is_rule_specific(tmp_path):
    fs = lint(tmp_path, """\
        import jax

        def f(x):
            return float(x)  # graftlint: disable=JG003

        jf = jax.jit(f)
        """, rules=["JG001"])
    assert len(fs) == 1  # wrong id suppresses nothing


def test_parse_suppressions():
    sup = parse_suppressions([
        "x = 1",
        "y = f(x)  # graftlint: disable=JG001,JG004",
        "z = g(y)  # graftlint: disable=all",
        "w = h(z)  # graftlint: disable=ALL",    # case-insensitive
        "v = k(w)  # graftlint: disable=jg003",
    ])
    assert sup == {2: {"JG001", "JG004"}, 3: {"all"}, 4: {"all"},
                   5: {"JG003"}}


def test_missing_scan_path_fails_loudly(tmp_path):
    # a typo'd CI target must not lint nothing and stay green
    r = _cli(str(tmp_path / "no_such_dir"))
    assert r.returncode == 2
    assert "does not exist" in r.stderr
    empty = tmp_path / "empty"
    empty.mkdir()
    r = _cli(str(empty))
    assert r.returncode == 2
    assert "no .py files" in r.stderr


def test_modnames_are_package_accurate_from_any_scan_root(tmp_path):
    # scanning a NON-package root (e.g. '.') must still resolve
    # cross-module absolute imports, or interprocedural taint silently
    # drops and real findings are missed
    pkg = tmp_path / "proj" / "mypkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(
        "def coerce(y):\n    return float(y)\n")
    (pkg / "entry.py").write_text(
        "import jax\n"
        "from mypkg.helpers import coerce\n\n"
        "def f(x):\n"
        "    return coerce(x)\n\n"
        "jf = jax.jit(f)\n")
    eng = LintEngine([str(tmp_path / "proj")], rules=["JG001"],
                     use_baseline=False)
    fs = [f for f in eng.run() if f.status == "new"]
    assert len(fs) == 1 and "coerce" in fs[0].message
    assert fs[0].path.endswith("mypkg/helpers.py")


def test_baseline_workflow(tmp_path):
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    bad = ("import jax\n\n"
           "def f(x):\n"
           "    return float(x)\n\n"
           "jf = jax.jit(f)\n")
    (pkg / "mod.py").write_text(bad)
    bl = tmp_path / "baseline.json"

    # 1. findings are new without a baseline
    eng = LintEngine([str(pkg)], baseline_path=str(bl))
    fs = eng.run()
    assert eng.stats["new"] == 1

    # 2. accept them; the next run is clean
    eng.update_baseline(fs)
    assert json.loads(bl.read_text())["findings"]
    eng2 = LintEngine([str(pkg)], baseline_path=str(bl))
    eng2.run()
    assert eng2.stats["new"] == 0 and eng2.stats["baselined"] == 1

    # 3. baseline keys survive line-number drift (same source line)
    (pkg / "mod.py").write_text("# a new leading comment\n" + bad)
    eng3 = LintEngine([str(pkg)], baseline_path=str(bl))
    eng3.run()
    assert eng3.stats["new"] == 0 and eng3.stats["baselined"] == 1

    # 4. a NEW finding is not absorbed by the old entry
    (pkg / "mod.py").write_text(
        bad + "\ndef g(y):\n    return int(y)\n\njg = jax.jit(g)\n")
    eng4 = LintEngine([str(pkg)], baseline_path=str(bl))
    eng4.run()
    assert eng4.stats["new"] == 1 and eng4.stats["baselined"] == 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=str(cwd), capture_output=True, text=True, timeout=120)


def test_cli_exit_codes_and_summary(tmp_path):
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import jax\n\ndef f(x):\n    return float(x)\n\njf = jax.jit(f)\n")
    bl = tmp_path / "baseline.json"

    r = _cli(str(pkg), "--baseline", str(bl))
    assert r.returncode == 1, r.stdout + r.stderr
    summary = r.stdout.strip().splitlines()[-1]
    assert re.match(r"^graftlint: files=\d+ rules=\d+ findings=\d+ "
                    r"baselined=\d+ suppressed=\d+ new=\d+ "
                    r"time=\d+\.\d+s$", summary), summary

    r = _cli(str(pkg), "--baseline", str(bl), "--update-baseline")
    assert r.returncode == 0
    r = _cli(str(pkg), "--baseline", str(bl))
    assert r.returncode == 0

    r = _cli(str(pkg), "--baseline", str(bl), "--no-baseline")
    assert r.returncode == 1  # --no-baseline resurfaces everything


def test_cli_json_and_list_rules(tmp_path):
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import jax\n\ndef f(x):\n    return float(x)\n\njf = jax.jit(f)\n")
    r = _cli(str(pkg), "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout[:r.stdout.rindex("}") + 1])
    assert payload["summary"]["new"] == 1
    assert payload["findings"][0]["rule"] == "JG001"

    r = _cli("--list-rules")
    assert r.returncode == 0
    for rid in ALL_RULES:
        assert rid in r.stdout
    assert set(ALL_RULES) == set(RULE_DOCS)

    r = _cli("--rules", "JG999", str(pkg))
    assert r.returncode == 2


# ---------------------------------------------------------------------------
# the shipped tree is clean (the CI gate, exercised in-process)
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean():
    eng = LintEngine(
        [str(REPO / "mxnet_tpu")],
        baseline_path=str(REPO / "tools" / "graftlint" / "baseline.json"))
    findings = eng.run()
    new = [f for f in findings if f.status == "new"]
    assert not new, "un-baselined graftlint findings:\n%s" % \
        "\n".join(repr(f) for f in new)


def test_shipped_tree_lint_is_fast():
    import time as _t
    t0 = _t.perf_counter()
    eng = LintEngine(
        [str(REPO / "mxnet_tpu")],
        baseline_path=str(REPO / "tools" / "graftlint" / "baseline.json"))
    eng.run()
    assert _t.perf_counter() - t0 < 10.0  # the CI fast-path budget


# ---------------------------------------------------------------------------
# registry cross-check: every register_op entry holds the JG005
# contract at runtime (new ops can't regress it)
# ---------------------------------------------------------------------------

_REGISTRATIONS = list(_reg.iter_registrations())


def test_registry_is_populated():
    assert len(_REGISTRATIONS) > 200


@pytest.mark.parametrize("name,op", _REGISTRATIONS,
                         ids=[n for n, _ in _REGISTRATIONS])
def test_registry_contract(name, op):
    c = _reg.op_contract(op)
    assert c["rng_param_ok"], (
        "op %r declares needs_rng but its kernel's first positional "
        "parameter %s is not an rng key name" %
        (name, c["positional_params"][:1]))
    assert c["donate_valid"], (
        "op %r: donate=%s addresses a nonexistent array input "
        "(array arity %s)" % (name, op.donate, c["array_arity"]))
    assert c["input_names_consistent"], (
        "op %r: input_names=%s is inconsistent with the kernel "
        "signature %s" % (name, op.input_names, c["positional_params"]))


# ---------------------------------------------------------------------------
# regression tests for the real findings this PR fixed — behavior is
# unchanged, only the silent-swallow hazard is gone
# ---------------------------------------------------------------------------

class TestFixedFindings:
    def test_ctx_of_still_defaults_for_abstract_values(self):
        # JG006 fix in ndarray/ndarray.py:_ctx_of (narrowed except):
        # values without .devices() still fall back to current_context
        import jax
        from mxnet_tpu.ndarray.ndarray import _ctx_of

        class NoDevices:
            pass

        assert _ctx_of(NoDevices()) == mx.current_context()
        arr = nd.ones((2,))
        assert _ctx_of(arr._data).device_type == "cpu"
        # real tracers raise ConcretizationTypeError (a TypeError
        # subclass) on .devices() — must still fall back, not raise
        seen = []

        def probe(x):
            seen.append(_ctx_of(x))
            return x

        jax.jit(probe)(arr._data)
        assert seen == [mx.current_context()]
        # deleted (donated) buffers raise RuntimeError — same fallback
        donated = jax.numpy.ones(2)
        donated.delete()
        assert _ctx_of(donated) == mx.current_context()

    def test_eval_shape_op_failure_still_returns_none(self, caplog):
        # JG006 fix in symbol/symbol.py:_eval_shape_op: a failing op
        # still yields unknown shapes (partial inference fills them
        # in), but the failure is now logged instead of vanishing
        from mxnet_tpu.symbol.symbol import _eval_shape_op

        class _Op:
            name = "boom_op"
            needs_rng = False

            @staticmethod
            def fn(*arrs, **params):
                raise ValueError("boom")

        class _Node:
            op = _Op()
            params = {}

            @staticmethod
            def num_outputs():
                return 2

        with caplog.at_level(logging.DEBUG, "mxnet_tpu.symbol.symbol"):
            out = _eval_shape_op(_Node(), [(2, 3)])
        assert out == [None, None]
        assert any("boom_op" in r.message for r in caplog.records)

    def test_materialize_eval_shape_fallback(self, caplog):
        # JG006 fix in executor.py:_materialize: when eval_shape fails,
        # the executed-forward fallback still produces ones cotangents
        # — and the failure is logged
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.executor import _materialize

        class _Ctx:
            jax_device = jax.devices("cpu")[0]

        class _Ex:
            _key = jax.random.PRNGKey(0)
            _ctx = _Ctx()

            @staticmethod
            def _eval_infer(arg_map, aux_map, key):
                raise ValueError("shape inference exploded")

            @staticmethod
            def _jit_infer(arg_map, aux_map, key):
                return [jnp.zeros((2, 3), jnp.float32)], None

        with caplog.at_level(logging.DEBUG, "mxnet_tpu.executor"):
            out = _materialize([None], _Ex(), {}, {})
        assert len(out) == 1 and out[0].shape == (2, 3)
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)
        assert any("eval_shape" in r.message for r in caplog.records)

    def test_backward_without_out_grads_mainline(self):
        # the mainline _materialize path (eval_shape succeeds) is
        # byte-for-byte the pre-fix behavior: backward() with no
        # out_grads trains against ones cotangents
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        out = sym.SoftmaxOutput(fc, name="softmax")
        ex = out.simple_bind(ctx=mx.cpu(), data=(2, 3),
                             softmax_label=(2,))
        rng = np.random.RandomState(0)
        ex.arg_dict["fc_weight"][:] = \
            rng.randn(4, 3).astype(np.float32) * .1
        ex.forward(is_train=True,
                   data=rng.randn(2, 3).astype(np.float32),
                   softmax_label=np.zeros((2,), np.float32))
        ex.backward()
        assert np.abs(ex.grad_dict["fc_weight"].asnumpy()).sum() > 0

    def test_trace_time_counters_still_count_compiles(self):
        # the three JG003 suppressions are deliberate: the counter
        # must bump exactly once per compile, not per step
        from mxnet_tpu import profiler as prof
        assert prof.counters().get("fused_step_compiles", 0) >= 0
