"""graftsched schedule-exploration suite (tools/graftsched + the
mxnet_tpu.sanitizer ``sched`` component).

Covers: scheduler-core determinism (same input, identical decision
sequence), bit-exact replay of a recorded trace, deadlock and livelock
reports carrying every live thread's stack, thread exceptions and
invariant violations surfacing as findings, the DPOR-pruned explorer
finding a seeded lost-update, trace round-tripping, zero wrappers when
``MXNET_SAN`` is unset, and pinned regressions for the real bugs the
explorer surfaced (the CheckpointManager unlocked pending-writers
bookkeeping and the kvstore applies-counter inflation)."""

import os
import threading

import pytest

from mxnet_tpu import sanitizer as san

import tools.graftsched.core as core
from tools.graftsched import explore


@pytest.fixture
def sched_on(monkeypatch):
    monkeypatch.setenv("MXNET_SAN", "sched")


@pytest.fixture(autouse=True)
def _no_leftover_scheduler():
    yield
    assert core.current() is None, "a test leaked an installed scheduler"


# ---------------------------------------------------------------------------
# toy scenarios
# ---------------------------------------------------------------------------

class _LostUpdate:
    """Two unsynchronized read-modify-writes on a tracked counter:
    somewhere in the schedule set the increments overlap and one is
    lost."""

    name = "toy-lost-update"
    budget = 64

    def run(self):
        class Box:
            counter = 0
        box = Box()
        san.track(box, ("counter",), label="box")

        def bump():
            v = box.counter
            box.counter = v + 1

        t1 = san.thread(target=bump, name="bump-1")
        t2 = san.thread(target=bump, name="bump-2")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        # hand check() the plain int: a tracked-object repr in the
        # assertion message would differ per run and defeat the
        # bit-exact replay comparison
        return int(box.counter)

    def check(self, counter):
        assert counter == 2, counter


class _Deadlock:
    name = "toy-deadlock"
    budget = 16

    def run(self):
        a = san.lock(label="A")
        b = san.lock(label="B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = san.thread(target=ab, name="ab")
        t2 = san.thread(target=ba, name="ba")
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        return None

    def check(self, state):
        pass


def _run_toy(factory, **kw):
    return explore.run_schedule(factory, **kw)


# ---------------------------------------------------------------------------
# determinism + replay
# ---------------------------------------------------------------------------

def test_same_input_identical_decision_sequence(sched_on):
    r1 = _run_toy(_LostUpdate)
    r2 = _run_toy(_LostUpdate)
    assert r1["decisions"] == r2["decisions"]
    assert len(r1["decisions"]) > 4
    # every decision is (tid, kind, key, reason)
    for d in r1["decisions"]:
        assert len(d) == 4 and isinstance(d[0], int)


def test_explorer_finds_lost_update_and_replay_is_bit_exact(
        sched_on, tmp_path):
    res = explore.explore(_LostUpdate, trace_dir=str(tmp_path))
    finding = res["finding"]
    assert finding is not None, "lost update not found"
    assert finding["type"] == "invariant"
    assert res["trace_path"] is not None

    trace = explore.load_trace(res["trace_path"])
    assert trace["scenario"] == "toy-lost-update"
    rep = explore.replay(_LostUpdate, trace)
    assert rep["finding"] is not None
    assert rep["finding"]["type"] == finding["type"]
    assert rep["finding"]["message"] == finding["message"]
    assert list(rep["decisions"]) == \
        [tuple(d) for d in trace["decisions"]]


def test_replay_divergence_is_reported(sched_on, tmp_path):
    res = explore.explore(_LostUpdate, trace_dir=str(tmp_path))
    trace = explore.load_trace(res["trace_path"])
    # doctor the recorded decisions: force an impossible grant early
    doctored = [list(d) for d in trace["decisions"]]
    doctored[2][0] = 99
    trace["decisions"] = doctored
    rep = explore.replay(_LostUpdate, trace)
    assert rep["finding"] is not None
    assert rep["finding"]["type"] == "divergence"


# ---------------------------------------------------------------------------
# deadlock / livelock / exception findings
# ---------------------------------------------------------------------------

def test_deadlock_report_carries_both_stacks(sched_on):
    res = explore.explore(_Deadlock)
    finding = res["finding"]
    assert finding is not None
    assert finding["type"] == "deadlock"
    live = {s["name"]: "\n".join(s["stack"])
            for s in finding["stacks"]}
    assert "ab" in live and "ba" in live, live.keys()
    # each stack points into the scenario body, not scheduler guts
    assert "ab()" in live["ab"] or "with b" in live["ab"], live["ab"]
    assert "ba()" in live["ba"] or "with a" in live["ba"], live["ba"]


def test_livelock_guard_reports_with_stacks(sched_on):
    class Spinner:
        name = "toy-livelock"

        def run(self):
            def spin():
                while True:
                    san.sched_point("spin")

            t = san.thread(target=spin, name="spinner")
            t.start()
            t.join()

        def check(self, state):
            pass

    res = explore.run_schedule(Spinner, max_steps=80)
    finding = res["finding"]
    assert finding is not None
    assert finding["type"] == "livelock"
    assert "80" in finding["message"]
    names = {s["name"] for s in finding["stacks"]}
    assert "spinner" in names
    spin_stack = "\n".join(
        s["stack"][-1] for s in finding["stacks"]
        if s["name"] == "spinner")
    assert "spin" in spin_stack


def test_thread_exception_becomes_finding(sched_on):
    class Boom:
        name = "toy-boom"

        def run(self):
            def die():
                raise ValueError("seeded boom")

            t = san.thread(target=die, name="dier")
            t.start()
            t.join()

        def check(self, state):
            pass

    res = explore.run_schedule(Boom)
    finding = res["finding"]
    assert finding is not None
    assert finding["type"] == "exception"
    assert "ValueError" in finding["message"]
    assert "seeded boom" in finding["message"]


def test_queue_and_event_primitives_schedule_cleanly(sched_on):
    class PingPong:
        name = "toy-queue"
        budget = 32

        def run(self):
            q = san.queue(maxsize=1)
            done = san.event()
            out = []

            def producer():
                for i in range(3):
                    q.put(i)
                done.set()

            def consumer():
                for _ in range(3):
                    out.append(q.get())
                done.wait()

            t1 = san.thread(target=producer, name="producer")
            t2 = san.thread(target=consumer, name="consumer")
            t1.start()
            t2.start()
            t1.join()
            t2.join()
            return out

        def check(self, out):
            assert out == [0, 1, 2], out

    res = explore.explore(PingPong)
    assert res["finding"] is None
    assert res["schedules"] > 1


# ---------------------------------------------------------------------------
# MXNET_SAN unset: plain primitives, no indirection
# ---------------------------------------------------------------------------

def test_unset_means_plain_primitives_and_noop_sched_point(monkeypatch):
    monkeypatch.delenv("MXNET_SAN", raising=False)
    assert type(san.lock()) is type(threading.Lock())
    assert isinstance(san.condition(), threading.Condition)
    assert type(san.event()) is threading.Event
    assert type(san.thread(target=lambda: None)) is threading.Thread
    san.sched_point("noop")     # must not raise, must not install

    class Obj:
        x = 0
    o = Obj()
    san.track(o, ("x",), "o")
    assert type(o) is Obj


def test_sched_alone_without_scheduler_stays_plain(monkeypatch):
    # MXNET_SAN=sched but no scheduler installed (normal pytest
    # thread): the factories must hand back plain primitives, not
    # reroute to a scheduler that is not there
    monkeypatch.setenv("MXNET_SAN", "sched")
    assert core.current_controlled() is None
    assert type(san.lock()) is type(threading.Lock())
    assert type(san.event()) is threading.Event
    san.sched_point("noop")


# ---------------------------------------------------------------------------
# pinned regressions: real bugs graftsched surfaced
# ---------------------------------------------------------------------------

def test_pinned_checkpoint_unlocked_pending_bookkeeping(
        sched_on, tmp_path):
    """The pre-fix CheckpointManager registered background writers
    with an UNLOCKED filter-then-reassign of ``_pending``: two
    concurrent saves could interleave so one writer thread vanished
    from the list, and ``wait()`` returned without joining it — the
    manifest then lacked that epoch.  Re-introduce the buggy shape in
    a subclass: graftsched must find it and the trace must replay."""
    import numpy as np
    from mxnet_tpu import nd
    from mxnet_tpu.resilience.checkpoint import CheckpointManager

    class Buggy(CheckpointManager):
        def save_background_buggy(self, epoch, arg_params):
            t = san.thread(
                target=CheckpointManager.save_checkpoint,
                args=(self, epoch),
                kwargs={"arg_params": arg_params,
                        "background": False})
            # pre-fix shape: no _plock around the read-filter-write
            self._pending = [p for p in self._pending
                             if p.is_alive()]
            self._pending.append(t)
            t.start()

    params = {"w": nd.array(np.arange(2, dtype=np.float32))}
    base = str(tmp_path)
    counter = [0]

    class Scenario:
        name = "pinned-checkpoint"
        budget = 64

        def run(self):
            counter[0] += 1
            prefix = os.path.join(base, "run%d" % counter[0], "model")
            os.makedirs(os.path.dirname(prefix))
            mgr = Buggy(prefix, keep_last=0, background=True)

            def save(epoch):
                mgr.save_background_buggy(epoch, params)

            t1 = san.thread(target=save, args=(1,), name="save-1")
            t2 = san.thread(target=save, args=(2,), name="save-2")
            t1.start()
            t2.start()
            t1.join()
            t2.join()
            mgr.wait()
            return sorted(mgr.epochs())

        def check(self, epochs):
            assert epochs == [1, 2], epochs

    res = explore.explore(Scenario, trace_dir=str(tmp_path))
    finding = res["finding"]
    assert finding is not None, \
        "the unlocked pending bookkeeping was not found"
    assert finding["type"] == "invariant"

    rep = explore.replay(Scenario, res["trace_path"])
    assert rep["finding"] is not None
    assert rep["finding"]["type"] == "invariant"

    # and the SHIPPED manager (locked bookkeeping) explores clean
    from tools.graftsched.scenarios.checkpoint import CheckpointScenario
    clean = explore.explore(CheckpointScenario, budget=24)
    assert clean["finding"] is None, clean["finding"]


def test_pinned_kvstore_applies_counts_only_real_mutations():
    """Found by the kvserver scenario: a dist_async push arriving
    before SET_OPT raises typed — but the pre-fix ``_apply`` had
    already bumped ``applies``, inflating the exactly-once proof
    counter (and snapshot accounting) with a mutation that never
    happened."""
    import numpy as np
    from mxnet_tpu._kvstore_impl import KVStoreServer, _MSG_PUSH
    from mxnet_tpu.base import MXNetError

    srv = KVStoreServer(sync_mode=False, num_workers=1)
    try:
        srv.store["w"] = __import__("mxnet_tpu").nd.ones((2,))
        with pytest.raises(MXNetError, match="before an optimizer"):
            srv._dispatch(_MSG_PUSH, {"req": (0, 1, 0), "key": "w"},
                          [np.ones((2,), np.float32)])
        assert srv.applies == 0, srv.applies      # nothing mutated
        assert srv.pushes_received == 1
    finally:
        srv.sock.close()
