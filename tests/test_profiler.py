"""Profiler + Monitor tests (reference strategy:
tests/python/unittest/test_profiler.py, monitor usage in test_monitor)."""

import json
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_profiler_chrome_trace(tmp_path):
    fn = str(tmp_path / "trace.json")
    profiler.reset()
    profiler.set_config(filename=fn, profile_imperative=True)
    profiler.set_state("run")
    a = mx.nd.array(np.random.randn(32, 32).astype(np.float32))
    b = mx.nd.array(np.random.randn(32, 32).astype(np.float32))
    for _ in range(3):
        c = mx.nd.dot(a, b)
        c = mx.nd.relu(c)
    c.asnumpy()
    with profiler.scope("user_block"):
        (a + b).asnumpy()
    path = profiler.dump()
    assert path == fn and os.path.exists(fn)
    data = json.load(open(fn))
    names = {e["name"] for e in data["traceEvents"]}
    assert "dot" in names
    assert "relu" in names or "Activation" in names
    assert "user_block" in names
    for e in data["traceEvents"]:
        assert "ts" in e and "ph" in e


def test_profiler_aggregate_stats():
    profiler.reset()
    profiler.set_config(filename="/tmp/_p.json")
    profiler.set_state("run")
    a = mx.nd.array(np.ones((8, 8), np.float32))
    for _ in range(5):
        (a * 2).asnumpy()
    profiler.set_state("stop")
    table = profiler.dumps()
    assert "_mul_scalar" in table
    assert "Calls" in table


def test_profiler_objects():
    profiler.reset()
    profiler.set_config(filename="/tmp/_p2.json")
    profiler.set_state("run")
    d = profiler.Domain("test")
    with profiler.Task("work", domain=d):
        pass
    c = profiler.Counter("steps", domain=d, value=0)
    c += 5
    c.decrement(1)
    m = profiler.Marker("here", domain=d)
    m.mark()
    profiler.set_state("stop")
    profiler.dump(finished=True)
    data = json.load(open("/tmp/_p2.json"))
    names = {e["name"] for e in data["traceEvents"]}
    assert "test::work" in names
    assert "test::steps" in names
    assert "test::here" in names


def test_monitor_taps_interior_ops():
    x = mx.sym.var("x")
    h = mx.sym.FullyConnected(x, num_hidden=4, name="fc1")
    out = mx.sym.Activation(h, act_type="relu", name="act1")
    exe = out.simple_bind(ctx=mx.cpu(), x=(2, 3))
    rs = np.random.RandomState(0)
    for n in exe.arg_dict:
        exe.arg_dict[n][:] = rs.randn(
            *exe.arg_dict[n].shape).astype(np.float32)
    mon = mx.Monitor(interval=1, pattern=".*", sort=True)
    mon.install(exe)
    mon.tic()
    exe.forward()
    res = mon.toc()
    names = [n for _, n, _ in res]
    assert "fc1_output" in names
    assert "act1_output" in names
    stats = {n: float(s) for _, n, s in res}
    assert stats["act1_output"] >= 0


def test_monitor_through_module():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=["softmax_label"])
    X = np.random.randn(8, 6).astype(np.float32)
    Y = np.zeros(8, np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=4,
                           label_name="softmax_label")
    mon = mx.Monitor(interval=1)
    mod.fit(it, num_epoch=1, optimizer="sgd", monitor=mon,
            optimizer_params={"learning_rate": 0.01})
