"""Image pipeline tests: mx.image, ImageRecordIter, device image ops
(reference strategy: tests/python/unittest/test_image.py + test_io.py
ImageRecordIter cases, on synthetic generated .rec files)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mimg
from mxnet_tpu import recordio

cv2 = pytest.importorskip("cv2")


def _make_img(h, w, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randint(0, 255, (h, w, 3), dtype=np.uint8)


def _encode(img):
    ok, buf = cv2.imencode(".jpg", cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
    assert ok
    return bytes(buf)


@pytest.fixture
def rec_file(tmp_path):
    """Synthetic 24-image .rec/.idx pair, labels 0..3."""
    prefix = str(tmp_path / "data")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    for i in range(24):
        img = _make_img(40 + i % 3, 36 + i % 5, seed=i)
        header = recordio.IRHeader(0, float(i % 4), i, 0)
        rec.write_idx(i, recordio.pack(header, _encode(img)))
    rec.close()
    return prefix


def test_imdecode_roundtrip():
    # smooth gradient — random noise is a JPEG worst case
    yy, xx = np.mgrid[0:32, 0:48]
    img = np.stack([yy * 8, xx * 5, (yy + xx) * 3],
                   axis=-1).astype(np.uint8)
    got = mimg.imdecode(_encode(img))
    assert got.shape == (32, 48, 3)
    assert np.abs(got.astype(int) - img.astype(int)).mean() < 4


def test_resize_and_crops():
    img = _make_img(40, 60)
    assert mimg.resize_short(img, 20).shape[0] == 20
    assert mimg.imresize(img, 10, 14).shape == (14, 10, 3)
    c, _ = mimg.center_crop(img, (30, 30))
    assert c.shape == (30, 30, 3)
    r, _ = mimg.random_crop(img, (20, 20))
    assert r.shape == (20, 20, 3)
    rs, _ = mimg.random_size_crop(img, (16, 16), (0.3, 1.0), (0.75, 1.33))
    assert rs.shape == (16, 16, 3)


def test_augmenter_list():
    augs = mimg.CreateAugmenter((3, 24, 24), resize=30, rand_crop=True,
                                rand_mirror=True, brightness=0.1,
                                contrast=0.1, saturation=0.1, hue=0.1,
                                pca_noise=0.05, rand_gray=0.1,
                                mean=True, std=True)
    img = _make_img(40, 50)
    out = img
    for a in augs:
        out = a(out)
    assert out.shape == (24, 24, 3)
    assert out.dtype == np.float32


def test_image_iter_list(tmp_path):
    paths = []
    for i in range(6):
        p = tmp_path / ("img%d.jpg" % i)
        cv2.imwrite(str(p), cv2.cvtColor(_make_img(30, 30, i),
                                         cv2.COLOR_RGB2BGR))
        paths.append(([float(i % 2)], str(p)))
    it = mimg.ImageIter(batch_size=3, data_shape=(3, 24, 24),
                        imglist=paths, path_root="")
    batch = next(it)
    assert batch.data[0].shape == (3, 3, 24, 24)
    assert batch.label[0].shape == (3,)


def test_image_record_iter(rec_file):
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_file + ".rec", path_imgidx=rec_file + ".idx",
        data_shape=(3, 24, 24), batch_size=8, shuffle=True,
        rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.28, mean_b=103.53,
        preprocess_threads=2)
    n = 0
    labels = []
    for batch in it:
        assert batch.data[0].shape == (8, 3, 24, 24)
        labels.append(batch.label[0].asnumpy())
        n += 1
    assert n == 3
    it.reset()
    assert sum(1 for _ in it) == 3
    assert set(np.concatenate(labels)) == {0.0, 1.0, 2.0, 3.0}


def test_image_record_iter_feeds_module(rec_file):
    """End-to-end: rec file -> ImageRecordIter -> conv net fit."""
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_file + ".rec", data_shape=(3, 16, 16),
        batch_size=8, std_r=58.4, std_g=57.1, std_b=57.4)
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    score = mod.score(it, mx.metric.Accuracy())
    assert score[0][1] >= 0.0  # ran end to end


def test_device_image_ops():
    img = _make_img(8, 6)
    x = mx.nd.array(img.astype(np.float32))
    t = mx.nd.image.to_tensor(mx.nd.array(img))
    assert t.shape == (3, 8, 6)
    np.testing.assert_allclose(t.asnumpy().max(), img.max() / 255.0,
                               rtol=1e-6)
    nrm = mx.nd.image.normalize(t, mean=(0.5, 0.5, 0.5),
                                std=(0.2, 0.2, 0.2))
    np.testing.assert_allclose(
        nrm.asnumpy(), (t.asnumpy() - 0.5) / 0.2, rtol=1e-5)
    f = mx.nd.image.flip_left_right(t)
    np.testing.assert_allclose(f.asnumpy(), t.asnumpy()[:, :, ::-1])


def test_im2rec_tool(tmp_path):
    import subprocess
    import sys
    root = tmp_path / "cls"
    for cls in ("a", "b"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            cv2.imwrite(str(d / ("%d.jpg" % i)),
                        cv2.cvtColor(_make_img(20, 20, i),
                                     cv2.COLOR_RGB2BGR))
    prefix = str(tmp_path / "out")
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "im2rec.py"), prefix, str(root)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               path_imgidx=prefix + ".idx",
                               data_shape=(3, 16, 16), batch_size=2)
    batch = next(it)
    assert batch.data[0].shape == (2, 3, 16, 16)
