"""Module API + the end-to-end MNIST slice
(reference: tests/python/unittest/test_module.py, tests/python/train/)."""

import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.io import NDArrayIter, MNISTIter


def _mlp_sym(num_hidden=32, num_classes=4):
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _lenet_sym():
    data = sym.var("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = sym.Flatten(p1)
    fc1 = sym.FullyConnected(f, num_hidden=32, name="fc1")
    a2 = sym.Activation(fc1, act_type="tanh")
    fc2 = sym.FullyConnected(a2, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def _toy_data(n=256, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3
    labels = rng.randint(0, classes, n)
    data = centers[labels] + rng.randn(n, dim)
    return data.astype(np.float32), labels.astype(np.float32)


def test_module_fit_toy():
    data, labels = _toy_data()
    train = NDArrayIter(data, labels, batch_size=32, shuffle=True)
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    score = mod.score(NDArrayIter(data, labels, batch_size=32), "acc")
    assert score[0][1] > 0.9, "toy problem should be learnable: %s" % score


def test_module_predict():
    data, labels = _toy_data(n=64)
    train = NDArrayIter(data, labels, batch_size=16)
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer="sgd")
    preds = mod.predict(NDArrayIter(data, labels, batch_size=16))
    assert preds.shape == (64, 4)


def test_module_checkpoint(tmp_path):
    data, labels = _toy_data(n=64)
    train = NDArrayIter(data, labels, batch_size=16)
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer="sgd")
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)

    mod2 = mx.Module.load(prefix, 1, context=mx.cpu())
    mod2.bind(train.provide_data, train.provide_label, for_training=False)
    p1 = mod.predict(NDArrayIter(data, labels, batch_size=16)).asnumpy()
    p2 = mod2.predict(NDArrayIter(data, labels, batch_size=16)).asnumpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_module_epoch_end_checkpoint(tmp_path):
    data, labels = _toy_data(n=64)
    train = NDArrayIter(data, labels, batch_size=16)
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    prefix = str(tmp_path / "cb")
    mod.fit(train, num_epoch=2,
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    s, a, x = mx.model.load_checkpoint(prefix, 2)
    assert "fc1_weight" in a


def test_module_input_grads():
    data, labels = _toy_data(n=32)
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    train = NDArrayIter(data, labels, batch_size=8)
    mod.bind(train.provide_data, train.provide_label,
             inputs_need_grad=True)
    mod.init_params()
    mod.init_optimizer()
    batch = next(iter(train))
    mod.forward_backward(batch)
    g = mod.get_input_grads()[0]
    assert g.shape == (8, 16)
    assert np.abs(g.asnumpy()).sum() > 0


def test_module_multi_device():
    """Data-parallel across 2 virtual devices (reference:
    DataParallelExecutorGroup semantics)."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    data, labels = _toy_data(n=128)
    train = NDArrayIter(data, labels, batch_size=32, shuffle=True)
    mod = mx.Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    score = mod.score(NDArrayIter(data, labels, batch_size=32), "acc")
    assert score[0][1] > 0.8


def _write_synth_mnist(tmp_path, n=512, seed=0):
    """Synthetic 'MNIST': each class k is a bright square in region k."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = (rng.rand(n, 12, 12) * 40).astype(np.uint8)
    for i, k in enumerate(labels):
        r, c = divmod(int(k), 4)
        images[i, 3 * r:3 * r + 4, 3 * c:3 * c + 4] = 220
    img = str(tmp_path / "train-images-idx3-ubyte")
    lbl = str(tmp_path / "train-labels-idx1-ubyte")
    with open(img, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 12, 12))
        f.write(images.tobytes())
    with open(lbl, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img, lbl


def test_lenet_mnist_end_to_end(tmp_path):
    """The SURVEY.md §7 step-3 milestone: MNISTIter -> LeNet -> Module.fit
    -> accuracy, exercising iterator, executor, optimizer, metric and
    checkpointing in one pass (reference: train_mnist.py)."""
    img, lbl = _write_synth_mnist(tmp_path)
    train = MNISTIter(image=img, label=lbl, batch_size=32, shuffle=True)
    val = MNISTIter(image=img, label=lbl, batch_size=32, shuffle=False)
    mod = mx.Module(_lenet_sym(), context=mx.cpu())
    mod.fit(train, eval_data=val, num_epoch=6, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    score = mod.score(val, "acc")
    assert score[0][1] > 0.9, "LeNet should learn synthetic MNIST: %s" % \
        score


class _RaggedIter:
    """Minimal inference iterator yielding a ragged last batch — what a
    caller streaming natural-sized requests through predict looks like."""

    def __init__(self, arrays):
        from mxnet_tpu.io import DataBatch
        self._batches = [DataBatch(data=[nd.array(a)]) for a in arrays]

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._batches)


def test_module_predict_ragged_remainder_single_compile():
    """Remainder fix-up regression (graftlint JG004 hazard): a ragged
    epoch — full batches plus every partial size — runs on EXACTLY one
    compiled inference program (the partials are zero-padded up to the
    bound batch and mask-trimmed), and each partial's rows are
    bit-identical to the same rows forwarded inside a full batch."""
    dim, bs = 16, 8
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.softmax(net)
    mod = mx.Module(net, label_names=None, context=mx.cpu())
    mod.bind(data_shapes=[("data", (bs, dim))], for_training=False)
    mod.init_params()
    from mxnet_tpu.io import DataBatch
    jit = mod._exec_group.execs[0]._jit_infer
    rs = np.random.RandomState(0)

    full = rs.randn(bs, dim).astype(np.float32)
    mod.forward(DataBatch(data=[nd.array(full)]))
    assert jit._cache_size() == 1

    for n in (5, 3, 1, 7, 2, 6):
        x = rs.randn(n, dim).astype(np.float32)
        mod.forward(DataBatch(data=[nd.array(x)]))
        out = mod.get_outputs()[0]
        assert out.shape == (n, 4)          # trimmed to the natural rows
        got = out.asnumpy()
        buf = np.zeros((bs, dim), np.float32)
        buf[:n] = x
        mod.forward(DataBatch(data=[nd.array(buf)]))
        ref = mod.get_outputs()[0].asnumpy()[:n]
        assert np.array_equal(got, ref)
    # the JG004 pin: 6 distinct remainder shapes, still ONE program
    assert jit._cache_size() == 1

    # predict over a ragged epoch merges trimmed outputs and compiles
    # nothing new either
    arrays = [rs.randn(bs, dim).astype(np.float32),
              rs.randn(bs, dim).astype(np.float32),
              rs.randn(3, dim).astype(np.float32)]
    preds = mod.predict(_RaggedIter(arrays))
    assert preds.shape == (2 * bs + 3, 4)
    assert jit._cache_size() == 1


def test_module_train_forward_not_padded():
    """Padding is an inference-path fix-up only: a training forward at
    a mismatched batch keeps its natural shape (training owns its batch
    geometry; silently padding would corrupt gradient scaling)."""
    dim, bs = 16, 8
    mod = mx.Module(_mlp_sym(), context=mx.cpu())
    data, labels = _toy_data(n=32)
    train = NDArrayIter(data, labels, batch_size=bs)
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params()
    from mxnet_tpu.io import DataBatch
    x = np.zeros((bs, dim), np.float32)
    mod.forward(DataBatch(data=[nd.array(x)],
                          label=[nd.zeros((bs,))]), is_train=True)
    assert mod.get_outputs()[0].shape[0] == bs
