"""INT8 quantization tests.

Reference: tests/python/quantization/test_quantization.py (quantized op
checks + quantize_model flow over quantize_graph_pass.cc).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib.quantization import quantize_model, quantize_symbol
from mxnet_tpu.io.io import DataBatch


def test_quantize_dequantize_roundtrip_int8():
    rs = np.random.RandomState(0)
    x = rs.uniform(-3, 3, (4, 5)).astype(np.float32)
    m = float(np.abs(x).max())
    q, lo, hi = nd.quantize(nd.array(x), nd.array(-m), nd.array(m),
                            out_type="int8")
    assert str(q.dtype) == "int8"
    back = nd.dequantize(q, lo, hi).asnumpy()
    np.testing.assert_allclose(back, x, atol=2 * m / 254)


def test_quantized_fc_matches_int_math():
    rs = np.random.RandomState(1)
    d = rs.randint(-127, 128, (2, 6)).astype(np.int8)
    w = rs.randint(-127, 128, (3, 6)).astype(np.int8)
    out, omin, omax = nd.quantized_fc(
        nd.array(d), nd.array(w), nd.array(-1.0), nd.array(1.0),
        nd.array(-1.0), nd.array(1.0), num_hidden=3)
    assert str(out.dtype) == "int32"
    expected = d.astype(np.int64) @ w.T.astype(np.int64)
    np.testing.assert_allclose(out.asnumpy(), expected)


def test_quantized_conv_matches_fp32():
    rs = np.random.RandomState(2)
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    w = rs.randn(4, 3, 3, 3).astype(np.float32) * 0.3
    mx_, mw = float(np.abs(x).max()), float(np.abs(w).max())
    qx = np.round(x * 127 / mx_).astype(np.int8)
    qw = np.round(w * 127 / mw).astype(np.int8)
    out, omin, omax = nd.quantized_conv(
        nd.array(qx), nd.array(qw), nd.array(-mx_), nd.array(mx_),
        nd.array(-mw), nd.array(mw), kernel=(3, 3), num_filter=4)
    deq = nd.dequantize(out, omin, omax).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=4, no_bias=True).asnumpy()
    err = np.abs(deq - ref).max() / np.abs(ref).max()
    assert err < 0.03, err


def test_quantized_pooling_int8():
    rs = np.random.RandomState(3)
    x = rs.randint(-127, 128, (1, 2, 4, 4)).astype(np.int8)
    out, _, _ = nd.quantized_pooling(
        nd.array(x), nd.array(-1.0), nd.array(1.0), kernel=(2, 2),
        stride=(2, 2), pool_type="max")
    assert str(out.dtype) == "int8"
    ref = x.reshape(1, 2, 2, 2, 2, 2).max((3, 5))
    np.testing.assert_allclose(out.asnumpy(), ref)


def _convnet():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                            name="c1")
    a1 = mx.sym.Activation(data=c1, act_type="relu")
    p1 = mx.sym.Pooling(data=a1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    f1 = mx.sym.FullyConnected(data=p1, num_hidden=10, name="f1")
    return f1


def _convnet_params(rs):
    return {
        "c1_weight": nd.array(rs.randn(8, 3, 3, 3).astype(np.float32)
                              * 0.2),
        "c1_bias": nd.array(rs.randn(8).astype(np.float32) * 0.1),
        "f1_weight": nd.array(rs.randn(10, 8 * 5 * 5).astype(np.float32)
                              * 0.1),
        "f1_bias": nd.array(rs.randn(10).astype(np.float32) * 0.1),
    }


class _OneBatch:
    def __init__(self, x):
        self._x = x
        self._done = False

    def reset(self):
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        self._done = True
        return DataBatch(data=[nd.array(self._x)])


@pytest.mark.parametrize("mode", ["none", "naive", "entropy"])
def test_quantize_model_close_to_fp32(mode):
    rs = np.random.RandomState(4)
    x = rs.randn(4, 3, 12, 12).astype(np.float32)
    sym = _convnet()
    arg_params = _convnet_params(rs)
    ref = sym.bind(args={**arg_params, "data": nd.array(x)}) \
        .forward()[0].asnumpy()
    qsym, qargs, _ = quantize_model(
        sym, arg_params, {}, calib_mode=mode,
        calib_data=_OneBatch(x) if mode != "none" else None)
    out = qsym.bind(args={**qargs, "data": nd.array(x)}) \
        .forward()[0].asnumpy()
    err = np.abs(out - ref).max() / np.abs(ref).max()
    assert err < 0.05, err
    # int8 weights actually replaced the fp32 ones
    args = qsym.list_arguments()
    assert "c1_weight_quantized" in args and "c1_weight" not in args
    assert str(qargs["c1_weight_quantized"].dtype) == "int8"


def test_quantize_symbol_excluded_layers_stay_fp32():
    sym = _convnet()
    qsym, points = quantize_symbol(sym, excluded_sym_names=("c1",))
    args = qsym.list_arguments()
    assert "c1_weight" in args            # untouched
    assert "f1_weight_quantized" in args  # quantized


def test_quantized_lenet_accuracy_close_to_fp32():
    """End-to-end: train fp32 LeNet on synthetic digits, quantize with
    naive calibration, accuracy within 2% of fp32 (reference:
    test_quantization.py quantized model accuracy checks)."""
    import sys
    sys.path.insert(0, os.path.join(REPO, "examples"))
    from train_mnist import synthetic_mnist

    x, y = synthetic_mnist(1024)
    it = mx.io.NDArrayIter(data=x, label=y, batch_size=64,
                           label_name="softmax_label")
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data=data, kernel=(5, 5), num_filter=8,
                            name="c1")
    t1 = mx.sym.Activation(data=c1, act_type="tanh")
    p1 = mx.sym.Pooling(data=t1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    fl = mx.sym.Flatten(data=p1)
    f1 = mx.sym.FullyConnected(data=fl, num_hidden=10, name="f1")
    net = mx.sym.SoftmaxOutput(data=f1, name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1,
                              "rescale_grad": 1.0 / 64})
    acc_fp32 = dict(mod.score(it, mx.metric.create("accuracy")))[
        "accuracy"]
    arg_params, aux_params = mod.get_params()

    # quantize the feature extractor (symbol up to logits)
    qsym, qargs, _ = quantize_model(
        f1, arg_params, aux_params, calib_mode="naive",
        calib_data=_OneBatch(x[:256]), num_calib_examples=256)
    qexe = qsym.bind(args={**qargs, "data": nd.array(x)})
    logits = qexe.forward()[0].asnumpy()
    acc_int8 = float((logits.argmax(1) == y).mean())
    assert acc_fp32 > 0.9
    assert acc_int8 >= acc_fp32 - 0.02, (acc_int8, acc_fp32)


import os  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entropy_calibration_clips_outliers():
    """KL calibration must choose a much tighter threshold than the
    naive max when the calibration data contains rare outliers
    (reference: calib_mode='entropy')."""
    from mxnet_tpu.contrib.quantization import _kl_optimal_threshold
    rs = np.random.RandomState(0)
    vals = np.abs(rs.randn(100000))
    with_outlier = np.concatenate([vals, [100.0]])
    hist, _ = np.histogram(with_outlier, bins=2048, range=(0.0, 100.0))
    i = _kl_optimal_threshold(hist)
    thr = i / 2048 * 100.0
    assert thr < 20.0, thr          # naive would use 100.0
    # and covers the bulk of the real distribution
    assert thr > np.percentile(vals, 99), thr
