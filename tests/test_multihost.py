"""Multi-host (multi-process) training over one global mesh
(parallel.multihost; SURVEY §5.8 — the DCN-scale story the reference
covers with ps-lite worker processes).

Two REAL processes x 4 virtual CPU devices join a jax.distributed
coordinator bootstrapped from the reference's DMLC_* env names, build
one 8-device global mesh, and train data-parallel with each process
feeding only its half of the batch.  The per-step losses must be
identical across processes (replicated SPMD state) AND match a
single-process run over the same global batch."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
pid = int(sys.argv[1])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["DMLC_PS_ROOT_URI"] = "localhost"
os.environ["DMLC_PS_ROOT_PORT"] = sys.argv[2]
os.environ["DMLC_NUM_WORKER"] = "2"
os.environ["DMLC_WORKER_ID"] = str(pid)
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from mxnet_tpu.parallel import multihost
assert multihost.init_multihost()
assert multihost.process_count() == 2
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.data_parallel import ParallelTrainer

mesh = multihost.global_mesh({"dp": -1})
assert len(list(mesh.devices.flat)) == 8
assert multihost.is_multihost_mesh(mesh)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
net.initialize()
tr = ParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     mesh=mesh)
rs = np.random.RandomState(0)
Xg = rs.randn(16, 8).astype(np.float32)
Yg = rs.randint(0, 4, (16,)).astype(np.float32)
lo = slice(pid * 8, (pid + 1) * 8)
x = mx.nd.array(Xg[lo]); y = mx.nd.array(Yg[lo])
losses = [float(np.asarray(tr.fit_batch(x, y))) for _ in range(5)]
print("LOSSES", " ".join("%%.7f" %% l for l in losses), flush=True)
# predict returns THIS process's rows of the global output
pred = tr.predict_batch(x)
assert np.asarray(pred._data).shape == (8, 4)
# frozen begin-states (fused RNN) follow the GLOBAL batch geometry
from mxnet_tpu.gluon.model_zoo.lm import get_lstm_lm
lnet = get_lstm_lm(12, 8, 1)
lnet.initialize()
ltr = ParallelTrainer(lnet, gluon.loss.SoftmaxCrossEntropyLoss(),
                      optimizer="sgd",
                      optimizer_params={"learning_rate": 0.1},
                      mesh=mesh)
xt = mx.nd.array(rs.randint(0, 12, (8, 4)).astype(np.float32))
yt = mx.nd.array(rs.randint(0, 12, (8, 4)).astype(np.float32))
l0 = float(np.asarray(ltr.fit_batch(xt, yt)))
assert np.isfinite(l0) and ltr._frozen
print("FROZEN-OK", flush=True)
""" % {"repo": _REPO}


def _single_process_reference():
    """Same model/batch on this process's own 8-device mesh."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import ParallelTrainer

    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    tr = ParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1},
                         mesh=make_mesh({"dp": 8}))
    rs = np.random.RandomState(0)
    Xg = rs.randn(16, 8).astype(np.float32)
    Yg = rs.randint(0, 4, (16,)).astype(np.float32)
    x = mx.nd.array(Xg)
    y = mx.nd.array(Yg)
    return [float(np.asarray(tr.fit_batch(x, y))) for _ in range(5)]


@pytest.mark.timeout(600)
@pytest.mark.skip(reason="multi-process SPMD computations are not implemented on the CPU backend of this jaxlib (XlaRuntimeError: Multiprocess computations aren't implemented on the CPU backend); needs a TPU-capable or newer-jaxlib image -- see docs/failure_baseline.md")
def test_two_process_global_mesh_matches_single_process():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(pid), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    losses = []
    for out in outs:
        line = [ln for ln in out.splitlines()
                if ln.startswith("LOSSES")][0]
        losses.append([float(v) for v in line.split()[1:]])
    # both processes observe the identical replicated loss curve
    np.testing.assert_allclose(losses[0], losses[1], rtol=0, atol=0)
    # and it matches single-process training on the same global batch
    # (init RNG is per-process deterministic, so weights start equal)
    ref = _single_process_reference()
    np.testing.assert_allclose(losses[0], ref, rtol=1e-5, atol=1e-6)


@pytest.mark.timeout(600)
@pytest.mark.skip(reason="multi-process SPMD computations are not implemented on the CPU backend of this jaxlib (XlaRuntimeError: Multiprocess computations aren't implemented on the CPU backend); needs a TPU-capable or newer-jaxlib image -- see docs/failure_baseline.md")
def test_launcher_no_server_mode_runs_multihost_example():
    """tools/launch.py -n 2 -s 0 bootstraps a pure jax.distributed
    worker group (no parameter servers) running
    examples/train_multihost.py to convergence on both ranks."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", "2", "-s", "0", "--", sys.executable,
         os.path.join(_REPO, "examples", "train_multihost.py"),
         "--num-steps", "12"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=_REPO)
    assert p.returncode == 0, (p.stdout[-1500:], p.stderr[-1500:])
    assert p.stdout.count("MULTIHOST-TRAIN-OK") == 2, p.stdout[-1500:]
