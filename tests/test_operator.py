"""Per-op numeric + gradient sweep via the test_utils oracle.

Reference strategy: tests/python/unittest/test_operator.py (7,213 LoC)
with check_numeric_gradient / check_symbolic_forward / check_consistency
from python/mxnet/test_utils.py.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu

sym = mx.sym


def _v(name="x"):
    return sym.var(name)


# --- gradient checks over the core op families ---------------------------

UNARY_GRAD_OPS = [
    ("relu", lambda x: sym.relu(x)),
    ("sigmoid", lambda x: sym.sigmoid(x)),
    ("tanh", lambda x: sym.tanh(x)),
    ("exp", lambda x: sym.exp(x)),
    ("log", lambda x: sym.log(sym.abs(x) + 1.2)),
    ("sqrt", lambda x: sym.sqrt(sym.abs(x) + 1.0)),
    ("square", lambda x: sym.square(x)),
    ("softmax", lambda x: sym.softmax(x)),
    ("log_softmax", lambda x: sym.log_softmax(x)),
]


@pytest.mark.parametrize("name,f", UNARY_GRAD_OPS,
                         ids=[n for n, _ in UNARY_GRAD_OPS])
def test_unary_gradients(name, f):
    x = np.random.randn(3, 4).astype(np.float64)
    tu.check_numeric_gradient(f(_v()), {"x": x})


BINARY_GRAD_OPS = [
    ("add", lambda a, b: a + b),
    ("sub", lambda a, b: a - b),
    ("mul", lambda a, b: a * b),
    ("div", lambda a, b: a / (b + 2.5)),
    ("dot", lambda a, b: sym.dot(a, b)),
    ("broadcast_add", lambda a, b: sym.broadcast_add(a, b)),
]


@pytest.mark.parametrize("name,f", BINARY_GRAD_OPS,
                         ids=[n for n, _ in BINARY_GRAD_OPS])
def test_binary_gradients(name, f):
    a = np.random.randn(3, 3).astype(np.float64)
    b = np.random.randn(3, 3).astype(np.float64)
    tu.check_numeric_gradient(f(sym.var("a"), sym.var("b")),
                              {"a": a, "b": b})


def test_fully_connected_gradient():
    out = sym.FullyConnected(_v(), sym.var("w"), sym.var("b"),
                             num_hidden=4)
    tu.check_numeric_gradient(out, {
        "x": np.random.randn(2, 3),
        "w": np.random.randn(4, 3),
        "b": np.random.randn(4)})


def test_convolution_gradient():
    out = sym.Convolution(_v(), sym.var("w"), sym.var("b"),
                          kernel=(3, 3), num_filter=2, pad=(1, 1))
    tu.check_numeric_gradient(out, {
        "x": np.random.randn(1, 2, 5, 5),
        "w": np.random.randn(2, 2, 3, 3),
        "b": np.random.randn(2)}, rtol=2e-2, atol=1e-3)


def test_pooling_gradient():
    out = sym.Pooling(_v(), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    tu.check_numeric_gradient(out, {"x": np.random.randn(1, 2, 4, 4)})


def test_layernorm_gradient():
    out = sym.LayerNorm(_v(), sym.var("g"), sym.var("b"))
    tu.check_numeric_gradient(out, {
        "x": np.random.randn(3, 5),
        "g": np.random.randn(5),
        "b": np.random.randn(5)}, rtol=2e-2, atol=1e-3)


def test_batchnorm_inference_forward():
    x = np.random.randn(2, 3, 4, 4).astype(np.float32)
    gamma = np.random.rand(3).astype(np.float32) + 0.5
    beta = np.random.randn(3).astype(np.float32)
    mean = np.random.randn(3).astype(np.float32)
    var = np.random.rand(3).astype(np.float32) + 0.5
    out = sym.BatchNorm(_v(), sym.var("gamma"), sym.var("beta"),
                        sym.var("mm"), sym.var("mv"), fix_gamma=False,
                        use_global_stats=True)
    expected = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + 1e-3) * gamma[None, :, None, None] + \
        beta[None, :, None, None]
    tu.check_symbolic_forward(
        out, {"x": x, "gamma": gamma, "beta": beta},
        [expected], aux_states={"mm": mean, "mv": var},
        rtol=1e-3, atol=1e-4)


def test_batchnorm_training_stats_large_mean():
    """The one-pass shifted batch statistics must not cancel when
    |mean| >> std (E[x^2]-E[x]^2 would), and must hold for any
    moving-mean state (fresh zeros or converged)."""
    from mxnet_tpu.ops.registry import get_op
    import jax.numpy as jnp
    bn = get_op("BatchNorm").fn
    rs = np.random.RandomState(0)
    x = (1000.0 + 0.1 * rs.randn(8, 4, 16, 16)).astype(np.float32)
    true_var = x.var(axis=(0, 2, 3))
    for mm0 in (0.0, 1000.0):
        _, mean, var, _, _ = bn(
            jnp.array(x), jnp.ones(4), jnp.zeros(4),
            jnp.full((4,), mm0), jnp.ones(4), eps=1e-5,
            fix_gamma=False, training=True)
        np.testing.assert_allclose(np.asarray(var), true_var, rtol=2e-2)
        np.testing.assert_allclose(np.asarray(mean),
                                   x.mean(axis=(0, 2, 3)), rtol=1e-5)


def test_reduce_gradients():
    for f in (lambda x: sym.sum(x, axis=1),
              lambda x: sym.mean(x, axis=0),
              lambda x: sym.max(x, axis=1),
              lambda x: sym.prod(x, axis=1)):
        x = np.random.rand(3, 4) + 0.5
        tu.check_numeric_gradient(f(_v()), {"x": x})


def test_transform_gradients():
    x = np.random.randn(2, 3, 4)
    for f in (lambda s: sym.transpose(s, axes=(2, 0, 1)),
              lambda s: sym.reshape(s, shape=(6, 4)),
              lambda s: sym.flip(s, axis=1),
              lambda s: sym.slice(s, begin=(0, 1, 0), end=(2, 3, 3))):
        tu.check_numeric_gradient(f(_v()), {"x": x})


def test_check_symbolic_backward():
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    out_grad = np.ones((2, 2))
    tu.check_symbolic_backward(sym.square(_v()), {"x": x}, [out_grad],
                               {"x": 2 * x})


def test_consistency_mlp():
    """Cross-backend (or determinism) oracle on a small MLP."""
    net = sym.FullyConnected(
        sym.Activation(
            sym.FullyConnected(_v(), sym.var("w0"), sym.var("b0"),
                               num_hidden=8),
            act_type="relu"),
        sym.var("w1"), sym.var("b1"), num_hidden=3)
    tu.check_consistency(net, shapes={
        "x": (4, 6), "w0": (8, 6), "b0": (8,),
        "w1": (3, 8), "b1": (3,)})


def test_consistency_conv():
    net = sym.Pooling(
        sym.Convolution(_v(), sym.var("w"), sym.var("b"), kernel=(3, 3),
                        num_filter=4, pad=(1, 1)),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    tu.check_consistency(net, shapes={
        "x": (2, 3, 8, 8), "w": (4, 3, 3, 3), "b": (4,)})


def test_rand_ndarray_and_assert():
    a = tu.rand_ndarray((4, 5))
    assert a.shape == (4, 5)
    tu.assert_almost_equal(a, a.asnumpy())
    r = tu.rand_ndarray((6, 4), stype="row_sparse", density=0.5)
    assert r.stype == "row_sparse"


def test_embedding_take_gradients():
    w = np.random.randn(7, 4)
    idx = np.array([0.0, 2.0, 5.0])
    out = sym.Embedding(sym.var("idx"), sym.var("w"), input_dim=7,
                        output_dim=4)
    tu.check_numeric_gradient(out, {"idx": idx, "w": w},
                              grad_nodes=["w"])
