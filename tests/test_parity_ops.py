"""Functional tests for the round-4 op-parity additions
(mxnet_tpu/ops/parity.py) — legacy layers, long-tail tensor ops,
multisample distributions, and the graph-level sparse ops that make
``mx.sym`` sparse configurations runnable."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_legacy_aliases_dispatch():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = nd.ones((2, 3))
    np.testing.assert_allclose(nd._Plus(a, b).asnumpy(),
                               a.asnumpy() + 1)
    np.testing.assert_allclose(nd._MulScalar(a, scalar=3).asnumpy(),
                               a.asnumpy() * 3)
    np.testing.assert_allclose(
        nd._Logical_And(a, b).asnumpy(),
        (a.asnumpy() != 0).astype(np.float32))
    np.testing.assert_allclose(nd.broadcast_plus(a, b).asnumpy(),
                               a.asnumpy() + 1)


def test_hard_sigmoid_and_shape_size_array():
    x = nd.array(np.array([-10.0, -1.0, 0.0, 1.0, 10.0], np.float32))
    got = nd.hard_sigmoid(x).asnumpy()
    np.testing.assert_allclose(got, np.clip(0.2 * x.asnumpy() + 0.5,
                                            0, 1))
    m = nd.zeros((2, 5, 3))
    np.testing.assert_array_equal(nd.shape_array(m).asnumpy(),
                                  [2, 5, 3])
    np.testing.assert_array_equal(nd.size_array(m).asnumpy(), [30])


def test_slice_assign_and_scalar():
    x = nd.zeros((4, 4))
    y = nd.ones((2, 2))
    out = nd._slice_assign(x, y, begin=(1, 1), end=(3, 3))
    want = np.zeros((4, 4), np.float32)
    want[1:3, 1:3] = 1
    np.testing.assert_array_equal(out.asnumpy(), want)
    out2 = nd._slice_assign_scalar(x, scalar=7.0, begin=(0, 2),
                                      end=(4, 4))
    want2 = np.zeros((4, 4), np.float32)
    want2[:, 2:] = 7
    np.testing.assert_array_equal(out2.asnumpy(), want2)


def test_crop_layer_center_and_like():
    data = nd.array(np.arange(2 * 3 * 6 * 6, dtype=np.float32)
                    .reshape(2, 3, 6, 6))
    out = nd.Crop(data, h_w=(2, 2), center_crop=True, num_args=1)
    np.testing.assert_array_equal(out.asnumpy(),
                                  data.asnumpy()[:, :, 2:4, 2:4])
    like = nd.zeros((2, 1, 4, 4))
    out2 = nd.Crop(data, like, offset=(1, 1), num_args=2)
    np.testing.assert_array_equal(out2.asnumpy(),
                                  data.asnumpy()[:, :, 1:5, 1:5])


def test_svm_output_forward_and_grad():
    from mxnet_tpu import autograd
    rs = np.random.RandomState(0)
    scores = nd.array(rs.randn(5, 4).astype(np.float32))
    label = nd.array(np.array([0, 1, 2, 3, 1], np.float32))
    scores.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(scores, label, margin=1.0,
                              regularization_coefficient=0.5)
    np.testing.assert_allclose(out.asnumpy(), scores.asnumpy())
    out.backward()
    g = scores.grad.asnumpy()
    # L2-SVM analytic gradient
    s = scores.asnumpy()
    li = label.asnumpy().astype(int)
    sy = s[np.arange(5), li][:, None]
    viol = np.maximum(1.0 - (sy - s), 0.0)
    viol[np.arange(5), li] = 0
    want = 2.0 * viol
    want[np.arange(5), li] = -want.sum(axis=1)
    np.testing.assert_allclose(g, 0.5 * want, rtol=1e-5, atol=1e-5)
    # the op ignores the incoming cotangent (reference semantics)
    assert np.isfinite(g).all()


def test_bipartite_matching_doc_example():
    s = nd.array(np.array([[0.5, 0.6], [0.1, 0.2], [0.3, 0.4]],
                          np.float32))
    x, y = nd._contrib_bipartite_matching(s, threshold=1e-12,
                                             is_ascend=False)
    np.testing.assert_array_equal(x.asnumpy(), [1, -1, 0])
    np.testing.assert_array_equal(y.asnumpy(), [2, 0])


def test_bipartite_matching_topk_and_threshold():
    s = nd.array(np.array([[0.9, 0.05], [0.8, 0.7]], np.float32))
    x, _ = nd._contrib_bipartite_matching(s, threshold=0.5, topk=1)
    # only the single best (0.9 at r0,c0) is taken
    np.testing.assert_array_equal(x.asnumpy(), [0, -1])


def test_multisample_distributions_moments():
    rng_shape = (3,)
    lam = nd.array(np.array([1.0, 4.0, 9.0], np.float32))
    out = nd._sample_exponential(lam, shape=(20000,)).asnumpy()
    np.testing.assert_allclose(out.mean(axis=1), 1.0 / lam.asnumpy(),
                               rtol=0.1)
    pois = nd._sample_poisson(lam, shape=(20000,)).asnumpy()
    np.testing.assert_allclose(pois.mean(axis=1), lam.asnumpy(),
                               rtol=0.1)
    k = nd.array(np.array([2.0, 5.0], np.float32))
    p = nd.array(np.array([0.4, 0.7], np.float32))
    nb = nd._sample_negative_binomial(k, p, shape=(20000,)).asnumpy()
    want_mean = k.asnumpy() * (1 - p.asnumpy()) / p.asnumpy()
    np.testing.assert_allclose(nb.mean(axis=1), want_mean, rtol=0.15)
    mu = nd.array(np.array([3.0, 8.0], np.float32))
    alpha = nd.array(np.array([0.3, 0.1], np.float32))
    gnb = nd._sample_generalized_negative_binomial(
        mu, alpha, shape=(20000,)).asnumpy()
    np.testing.assert_allclose(gnb.mean(axis=1), mu.asnumpy(), rtol=0.15)


def test_group_adagrad_update():
    w = nd.array(np.ones((3, 4), np.float32))
    g = nd.array(np.full((3, 4), 2.0, np.float32))
    h = nd.zeros((3,))
    out = nd._contrib_group_adagrad_update(
        w, g, h, lr=0.1, rescale_grad=1.0, epsilon=1e-5)
    # history[r] = mean(4.0) = 4; w -= 0.1 * 2 / sqrt(4 + eps)
    want = 1.0 - 0.1 * 2.0 / np.sqrt(4.0 + 1e-5)
    np.testing.assert_allclose(out.asnumpy(),
                               np.full((3, 4), want), rtol=1e-6)


def test_deformable_psroi_pooling_zero_trans_matches_uniform():
    # with zero offsets each bin averages its own window; a constant
    # per-channel input must pool to that constant
    od, g, k = 2, 2, 2
    C = od * g * g
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c + 1
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    trans = nd.zeros((1, 2, k, k))
    out = nd._contrib_DeformablePSROIPooling(
        nd.array(data), rois, trans, spatial_scale=1.0, output_dim=od,
        group_size=g, pooled_size=k, part_size=k, sample_per_part=2,
        trans_std=0.1)
    got = out.asnumpy()
    # channel for (class c, bin i, j) is c*g*g + i*g + j -> value c*4+i*2+j+1
    want = np.array([[[1, 2], [3, 4]], [[5, 6], [7, 8]]], np.float32)
    np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-5)


# --- graph-level sparse ops + symbolic sparse linear classification ----


def test_sparse_graph_ops_nd():
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(5, 3).astype(np.float32))
    np.testing.assert_array_equal(
        nd.cast_storage(x, stype="row_sparse").asnumpy(), x.asnumpy())
    kept = nd._sparse_retain(x, nd.array(np.array([1, 3], np.float32)))
    want = np.zeros((5, 3), np.float32)
    want[[1, 3]] = x.asnumpy()[[1, 3]]
    np.testing.assert_array_equal(kept.asnumpy(), want)
    ss = nd._square_sum(x, axis=1)
    np.testing.assert_allclose(ss.asnumpy(), (x.asnumpy() ** 2).sum(1),
                               rtol=1e-5)


def test_symbolic_sparse_linear_classification():
    """LibSVM-style config under mx.sym/Module: dot(csr-style data, w)
    with cast_storage/_square_sum in the graph (the reference's
    example/sparse/linear_classification shape)."""
    import mxnet_tpu.optimizer as opt
    rs = np.random.RandomState(0)
    n, d = 64, 20
    w_true = rs.randn(d).astype(np.float32)
    xs = rs.randn(n, d).astype(np.float32)
    xs[rs.rand(n, d) > 0.3] = 0          # sparse-looking features
    ys = (xs @ w_true > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    weight = mx.sym.Variable("weight", shape=(d, 2))
    dense = mx.sym.cast_storage(data, stype="default")
    logits = mx.sym.dot(dense, weight)
    out = mx.sym.SoftmaxOutput(logits, mx.sym.Variable("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    from mxnet_tpu.io import NDArrayIter
    it = NDArrayIter(xs, ys, batch_size=16, shuffle=False,
                     label_name="softmax_label")
    mod.fit(it, num_epoch=12,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="acc")
    score = mod.score(it, "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.8, acc