"""Metrics (reference: tests/python/unittest/test_metric.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, metric


def test_accuracy():
    m = metric.create("acc")
    pred = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    name, value = m.get()
    assert name == "accuracy"
    np.testing.assert_allclose(value, 2.0 / 3)


def test_topk():
    m = metric.create("top_k_accuracy", top_k=2)
    pred = nd.array([[0.1, 0.2, 0.7], [0.8, 0.05, 0.15]])
    label = nd.array([1, 1])  # row0 top2={2,1}: hit; row1 top2={0,2}: miss
    m.update([label], [pred])
    _, value = m.get()
    np.testing.assert_allclose(value, 0.5)


def test_mse_mae_rmse():
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([1.5, 1.0])
    m = metric.create("mse")
    m.update([label], [pred])
    np.testing.assert_allclose(m.get()[1], (0.25 + 1.0) / 2)
    m = metric.create("mae")
    m.update([label], [pred])
    np.testing.assert_allclose(m.get()[1], (0.5 + 1.0) / 2)


def test_perplexity():
    m = metric.create("Perplexity", ignore_label=None)
    pred = nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = nd.array([0, 0])
    m.update([label], [pred])
    expected = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    np.testing.assert_allclose(m.get()[1], expected, rtol=1e-5)


def test_composite():
    m = metric.create(["acc", "mse"])
    assert isinstance(m, metric.CompositeEvalMetric)
    names, values = m.get()
    assert len(names) == 2


def test_custom_metric():
    def my_metric(label, pred):
        return float(np.abs(label - pred).sum())

    m = metric.np(my_metric)
    m.update([nd.array([1.0])], [nd.array([0.0])])
    assert m.get()[1] == 1.0


def test_cross_entropy():
    m = metric.create("ce")
    pred = nd.array([[0.25, 0.75]])
    label = nd.array([1])
    m.update([label], [pred])
    np.testing.assert_allclose(m.get()[1], -np.log(0.75), rtol=1e-5)


def test_f1():
    m = metric.F1()
    pred = nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    assert 0 < m.get()[1] <= 1.0


def test_column_vector_labels_all_classifiers():
    """(N, 1)-shaped label columns (a common iterator output) must work
    in every classification metric and stay within [0, 1]."""
    rs = np.random.RandomState(0)
    preds = nd.array(rs.rand(6, 2).astype(np.float32))
    lab_col = nd.array(rs.randint(0, 2, (6, 1)).astype(np.float32))
    for name in ("acc", "f1", "mcc"):
        m = metric.create(name)
        m.update([lab_col], [preds])
        v = m.get()[1]
        assert np.isfinite(v) and abs(v) <= 1.0, (name, v)
    mk = metric.create("top_k_accuracy", top_k=2)
    mk.update([lab_col], [nd.array(rs.rand(6, 5).astype(np.float32))])
    assert 0.0 <= mk.get()[1] <= 1.0
