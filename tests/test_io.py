"""Data IO (reference: tests/python/unittest/test_io.py)."""

import gzip
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import (NDArrayIter, MNISTIter, CSVIter, LibSVMIter,
                          ResizeIter, PrefetchingIter, DataBatch)


def _write_idx(tmp_path, n=50, rows=8, cols=8, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, (n, rows, cols), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,), dtype=np.uint8)
    img_path = str(tmp_path / "train-images-idx3-ubyte")
    lbl_path = str(tmp_path / "train-labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path, images, labels


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4

    it = NDArrayIter(data, label, batch_size=3,
                     last_batch_handle="discard")
    assert len(list(it)) == 3


def test_ndarray_iter_shuffle():
    data = np.arange(20).reshape(20, 1).astype(np.float32)
    it = NDArrayIter(data, data[:, 0], batch_size=5, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy()[:, 0] for b in it])
    assert sorted(seen.tolist()) == list(range(20))


def test_provide_data_label():
    it = NDArrayIter(np.zeros((8, 3)), np.zeros(8), batch_size=4)
    assert it.provide_data[0].name == "data"
    assert it.provide_data[0].shape == (4, 3)
    assert it.provide_label[0].name == "softmax_label"


def test_mnist_iter(tmp_path):
    img, lbl, images, labels = _write_idx(tmp_path)
    it = MNISTIter(image=img, label=lbl, batch_size=10, shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (10, 1, 8, 8)
    np.testing.assert_allclose(batch.data[0].asnumpy()[0, 0],
                               images[0] / 255.0, rtol=1e-6)
    np.testing.assert_allclose(batch.label[0].asnumpy(), labels[:10])
    # flat mode
    it = MNISTIter(image=img, label=lbl, batch_size=10, flat=True,
                   shuffle=False)
    assert next(iter(it)).data[0].shape == (10, 64)


def test_mnist_iter_gz(tmp_path):
    img, lbl, images, labels = _write_idx(tmp_path)
    for p in (img, lbl):
        with open(p, "rb") as fin, gzip.open(p + ".gz", "wb") as fout:
            fout.write(fin.read())
        os.remove(p)
    it = MNISTIter(image=img + ".gz", label=lbl + ".gz", batch_size=5,
                   shuffle=False)
    assert next(iter(it)).data[0].shape == (5, 1, 8, 8)


def test_csv_iter(tmp_path):
    data = np.random.rand(12, 3).astype(np.float32)
    label = np.arange(12).astype(np.float32)
    dpath = str(tmp_path / "data.csv")
    lpath = str(tmp_path / "label.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, label, delimiter=",")
    it = CSVIter(data_csv=dpath, data_shape=(3,), label_csv=lpath,
                 batch_size=4)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3)
    np.testing.assert_allclose(batch.data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:3.0\n")
        f.write("1 2:4.0 3:1.0\n")
        f.write("0 0:2.5\n")
    it = LibSVMIter(data_libsvm=path, data_shape=(4,), batch_size=2)
    batch = next(iter(it))
    assert batch.data[0].stype == "csr"
    dense = batch.data[0].asnumpy()
    np.testing.assert_allclose(dense, [[1.5, 0, 0, 2.0], [0, 3.0, 0, 0]])
    np.testing.assert_allclose(batch.label[0].asnumpy(), [1, 0])


def test_resize_iter():
    it = NDArrayIter(np.zeros((10, 2)), np.zeros(10), batch_size=5)
    resized = ResizeIter(it, 5)
    assert len(list(resized)) == 5


def test_prefetching_iter():
    it = NDArrayIter(np.arange(40).reshape(20, 2).astype(np.float32),
                     np.zeros(20), batch_size=5)
    pre = PrefetchingIter(it)
    batches = []
    while True:
        try:
            batches.append(pre.next())
        except StopIteration:
            break
    assert len(batches) == 4
    pre.reset()
    count = 0
    while True:
        try:
            pre.next()
            count += 1
        except StopIteration:
            break
    assert count == 4


class _DyingIter(NDArrayIter):
    """Inner iterator whose worker 'dies' (raises) on one batch of the
    first epoch, then behaves after reset — the prefetch thread must
    surface the exception and stay recoverable."""

    def __init__(self, fail_at=2, exc=RuntimeError("worker died"),
                 **kwargs):
        self._fail_at = fail_at
        self._exc = exc
        self._served = 0
        self._failed_once = False
        super().__init__(**kwargs)

    def next(self):
        if not self._failed_once and self._served == self._fail_at:
            self._failed_once = True
            raise self._exc
        self._served += 1
        return super().next()


def _dying_iter(fail_at=2, exc=None):
    return _DyingIter(
        fail_at=fail_at, exc=exc or RuntimeError("worker died"),
        data=np.arange(40).reshape(20, 2).astype(np.float32),
        label=np.zeros(20), batch_size=5)


def test_prefetch_worker_death_reaches_consumer_then_reset_recovers():
    """SATELLITE: an in-flight exception in the prefetch producer must
    reach the consumer — and a subsequent reset() must neither
    deadlock nor replay stale state."""
    pre = PrefetchingIter(_dying_iter(fail_at=2))
    assert pre.next() is not None
    assert pre.next() is not None
    with pytest.raises(RuntimeError, match="worker died"):
        pre.next()
    # the producer is gone; further next() calls must END the epoch,
    # not hang on an empty queue forever
    with pytest.raises(StopIteration):
        pre.next()
    pre.reset()           # must return promptly (bounded drain+join)
    count = 0
    while True:
        try:
            pre.next()
            count += 1
        except StopIteration:
            break
    assert count == 4     # full epoch after recovery
    pre.reset()
    assert pre.iter_next()


def test_prefetch_reset_while_producer_blocked_on_full_queue():
    """reset() with the producer wedged in put() (slow consumer, full
    queue) must drain it loose and come back — the historical deadlock
    shape."""
    inner = NDArrayIter(np.arange(80).reshape(40, 2).astype(np.float32),
                        np.zeros(40), batch_size=5)
    pre = PrefetchingIter(inner, prefetch_depth=1)
    import time
    time.sleep(0.1)       # let the producer fill the depth-1 queue
    pre.reset()           # producer is mid-put: must not deadlock
    batches = []
    while True:
        try:
            batches.append(pre.next())
        except StopIteration:
            break
    assert len(batches) == 8


def test_prefetch_exception_during_iteration_then_iter_next_protocol():
    """iter_next() (peek form) after a producer death reports False
    instead of raising through the peek path twice."""
    pre = PrefetchingIter(_dying_iter(fail_at=0))
    with pytest.raises(RuntimeError, match="worker died"):
        pre.next()
    assert pre.iter_next() is False
    pre.reset()
    assert pre.iter_next() is True


def test_prefetch_retry_spec_recovers_transient_failures():
    """A retry spec turns transient inner-iterator failures into
    backoff+retry instead of an epoch-ending exception."""
    sleeps = []
    pre = PrefetchingIter(
        _dying_iter(fail_at=2, exc=OSError("transient storage flake")),
        retry=dict(attempts=3, retry_on=(OSError,),
                   sleep=sleeps.append))
    batches = []
    while True:
        try:
            batches.append(pre.next())
        except StopIteration:
            break
    assert len(batches) == 4          # nothing lost
    assert len(sleeps) == 1           # exactly one backoff happened


def test_recordio(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(b"record%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == b"record%d" % i
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        writer.write_idx(i, b"payload%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert reader.read_idx(3) == b"payload3"
    assert reader.read_idx(0) == b"payload0"
    reader.close()


def test_recordio_pack_unpack():
    from mxnet_tpu import recordio
    header = recordio.IRHeader(0, 7.0, 42, 0)
    s = recordio.pack(header, b"imagebytes")
    h2, payload = recordio.unpack(s)
    assert h2.label == 7.0
    assert h2.id == 42
    assert payload == b"imagebytes"
    # vector label
    header = recordio.IRHeader(0, [1.0, 2.0, 3.0], 1, 0)
    s = recordio.pack(header, b"x")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1, 2, 3])


def test_gluon_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    x = np.random.rand(20, 3).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = ArrayDataset(x, y)
    assert len(ds) == 20
    loader = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 5
    data, label = batches[0]
    assert data.shape == (4, 3)
    np.testing.assert_allclose(data.asnumpy(), x[:4], rtol=1e-6)
    # threaded
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    assert len(list(loader)) == 5


def test_gluon_dataset_transform():
    from mxnet_tpu.gluon.data import ArrayDataset
    ds = ArrayDataset(np.arange(10).astype(np.float32))
    ds2 = ds.transform(lambda x: x * 2)
    assert ds2[3] == 6.0


def test_batch_sampler():
    from mxnet_tpu.gluon.data import BatchSampler, SequentialSampler
    bs = BatchSampler(SequentialSampler(10), 3, "keep")
    assert len(list(bs)) == 4
    bs = BatchSampler(SequentialSampler(10), 3, "discard")
    assert len(list(bs)) == 3


# --- multiprocess shared-memory DataLoader (reference: gluon/data/
# dataloader.py:26-110 cpu_shared worker IPC) ------------------------------

def _double_sample(x):
    return x * 2


class _FailingDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at index 5")
        return np.float32(i)


def test_dataloader_process_workers_shared_memory():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    x = np.arange(60).reshape(20, 3).astype(np.float32)
    y = np.arange(20).astype(np.float32)
    ds = ArrayDataset(x, y)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    batches = list(loader)
    assert len(batches) == 5
    # ordering must be preserved across out-of-order worker completion
    for i, (data, label) in enumerate(batches):
        np.testing.assert_allclose(data.asnumpy(), x[4 * i:4 * i + 4],
                                   rtol=1e-6)
        np.testing.assert_allclose(label.asnumpy(), y[4 * i:4 * i + 4],
                                   rtol=1e-6)
    # second epoch over the same loader works (fresh worker pool)
    assert len(list(loader)) == 5


def test_dataloader_process_worker_exception_propagates():
    from mxnet_tpu.gluon.data import DataLoader
    loader = DataLoader(_FailingDataset(), batch_size=4, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at index 5"):
        list(loader)


def test_dataloader_unpicklable_falls_back_to_threads():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(np.arange(12).astype(np.float32)).transform(
        lambda x: x + 1)  # lambda => not picklable
    with pytest.warns(UserWarning, match="not picklable"):
        out = list(DataLoader(ds, batch_size=3, num_workers=2))
    assert len(out) == 4
    np.testing.assert_allclose(out[0].asnumpy(), [1, 2, 3], rtol=1e-6)


# --- native C++ RecordIO reader (src/io/recordio_reader.cc) ---------------

def _native_built():
    from mxnet_tpu import recordio_native
    if not recordio_native.available():
        import subprocess as sp
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            sp.run(["make", "-C", os.path.join(repo, "src", "io")],
                   check=True, capture_output=True)
        except Exception:
            return False
        recordio_native._LIB = None
    return recordio_native.available()


def test_native_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio, recordio_native
    if not _native_built():
        pytest.skip("no C++ toolchain")
    p = str(tmp_path / "t.rec")
    recs = [b"hello", b"x" * 7, b"", b"payload" * 1000]
    w = recordio.MXRecordIO(p, "w")
    for r in recs:
        w.write(r)
    w.close()
    r = recordio_native.NativeRecordReader(p)
    got = []
    while True:
        b = r.read()
        if b is None:
            break
        got.append(b)
    assert got == recs
    offs = recordio_native.build_index(p)
    assert len(offs) == len(recs)
    assert r.read_idx(offs[2]) == recs[2]
    r.close()


def test_native_recordio_multipart_reassembly(tmp_path):
    # hand-craft a multi-part record (cflag 1/2/3 framing) — the python
    # writer never emits these but the reference reader handles them
    import struct
    if not _native_built():
        pytest.skip("no C++ toolchain")
    from mxnet_tpu import recordio_native
    p = str(tmp_path / "mp.rec")
    magic = 0xced7230a
    parts = [(1, b"abcd"), (2, b"efgh"), (3, b"ij")]
    with open(p, "wb") as f:
        for cflag, data in parts:
            f.write(struct.pack("<II", magic, (cflag << 29) | len(data)))
            f.write(data)
            pad = (4 - len(data) % 4) % 4
            f.write(b"\x00" * pad)
    r = recordio_native.NativeRecordReader(p)
    assert r.read() == b"abcdefghij"
    assert r.read() is None
    r.close()


def test_mxrecordio_uses_native_reader(tmp_path, monkeypatch):
    from mxnet_tpu import recordio
    if not _native_built():
        pytest.skip("no C++ toolchain")
    p = str(tmp_path / "n.rec")
    w = recordio.MXRecordIO(p, "w")
    w.write(b"one")
    w.write(b"two")
    w.close()
    r = recordio.MXRecordIO(p, "r")
    assert r._native is not None  # native path active by default
    assert r.read() == b"one" and r.read() == b"two"
    r.close()
    monkeypatch.setenv("MXNET_USE_NATIVE_RECORDIO", "0")
    r = recordio.MXRecordIO(p, "r")
    assert r._native is None
    assert r.read() == b"one"
    r.close()


def test_native_recordio_closed_handle_raises(tmp_path):
    from mxnet_tpu import recordio, recordio_native
    if not _native_built():
        pytest.skip("no C++ toolchain")
    p = str(tmp_path / "c.rec")
    w = recordio.MXRecordIO(p, "w")
    w.write(b"x")
    w.close()
    r = recordio_native.NativeRecordReader(p)
    r.close()
    with pytest.raises(IOError, match="closed"):
        r.read()
    with pytest.raises(IOError, match="closed"):
        r.tell()


def test_native_recordio_corrupt_length_rejected(tmp_path):
    import struct
    from mxnet_tpu import recordio_native
    if not _native_built():
        pytest.skip("no C++ toolchain")
    p = str(tmp_path / "bad.rec")
    with open(p, "wb") as f:
        # header claims a ~512MB record in a 16-byte file
        f.write(struct.pack("<II", 0xced7230a, (1 << 29) - 1))
        f.write(b"tiny")
    r = recordio_native.NativeRecordReader(p)
    with pytest.raises(IOError, match="exceeds file size"):
        r.read()
    r.close()


def test_rec2idx_and_parse_log_tools(tmp_path):
    """tools/rec2idx.py rebuilds a working .idx; tools/parse_log.py
    tabulates Speedometer/epoch log lines (reference: tools/rec2idx.py,
    tools/parse_log.py)."""
    import subprocess
    import sys
    from mxnet_tpu import recordio
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rec = recordio.MXRecordIO(str(tmp_path / "d.rec"), "w")
    for i in range(5):
        rec.write(b"payload-%d" % i)
    rec.close()
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "rec2idx.py"),
         str(tmp_path / "d.rec")], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    reader = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"),
                                        str(tmp_path / "d.rec"), "r")
    assert reader.read_idx(3) == b"payload-3"

    log = tmp_path / "t.log"
    log.write_text(
        "INFO:root:Epoch[0] Batch [20]\tSpeed: 100.0 samples/sec\n"
        "INFO:root:Epoch[0] Batch [40]\tSpeed: 140.0 samples/sec\n"
        "INFO:root:Epoch[0] Train-accuracy=0.61\n"
        "INFO:root:Epoch[0] Time cost=9.5\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.55\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "parse_log.py"),
         str(log), "--format", "csv"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "0,120.0,9.5,0.61,0.55" in r.stdout


def test_native_decode_matches_cv2_path(tmp_path):
    """The libjpeg worker-team fast path (src/io/jpeg_decode_pool.cc)
    produces the same batches as the cv2 augmenter chain for the plain
    classification config, modulo decoder/interpolation differences
    (fractional-DCT scaled decode vs cv2's full decode)."""
    import subprocess

    from mxnet_tpu.io.native_decode import available
    if not available():
        r = subprocess.run(["make", "-C",
                            os.path.join(REPO, "src", "io")],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]

    import cv2
    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter

    rs = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(
        str(tmp_path / "d.idx"), str(tmp_path / "d.rec"), "w")
    for i in range(8):
        # smooth gradient images keep decoder differences small
        yy, xx = np.mgrid[0:400, 0:500]
        img = np.stack([(yy * 0.5 + i * 9) % 256,
                        (xx * 0.4) % 256,
                        ((yy + xx) * 0.3) % 256], -1).astype(np.uint8)
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=95))
    rec.close()

    def batch_with(native):
        env_val = os.environ.get("MXNET_TPU_NATIVE_DECODE")
        os.environ["MXNET_TPU_NATIVE_DECODE"] = "1" if native else "0"
        try:
            it = ImageRecordIter(
                path_imgrec=str(tmp_path / "d.rec"),
                path_imgidx=str(tmp_path / "d.idx"),
                data_shape=(3, 224, 224), batch_size=8, resize=256,
                mean_r=123.68, mean_g=116.78, mean_b=103.94)
            return next(iter(it)).data[0].asnumpy()
        finally:
            if env_val is None:
                os.environ.pop("MXNET_TPU_NATIVE_DECODE", None)
            else:
                os.environ["MXNET_TPU_NATIVE_DECODE"] = env_val

    a = batch_with(native=True)
    b = batch_with(native=False)
    assert a.shape == b.shape == (8, 3, 224, 224)
    # same labels/geometry; pixel values agree within decoder tolerance
    assert np.abs(a - b).mean() < 8.0, np.abs(a - b).mean()
